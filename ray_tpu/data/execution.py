"""Streaming operator topology + autoscaling actor pools for ray_tpu.data.

The execution half of Ray Data, rebuilt for this runtime (reference
capabilities: python/ray/data/_internal/execution/ —
streaming_executor_state.py select_operator_to_run:626,
actor_pool_map_operator.py:77 with locality ranking :380-429,
resource_manager.py:55 memory budgets):

- a Dataset plan compiles to STAGES: consecutive row/batch task ops fuse
  into one task stage (one remote call per block); a ``map_batches`` with
  an actor compute strategy forms its own stage backed by an autoscaling
  actor pool (stateful / callable-class UDFs run here).
- consumption runs all stages as one pipeline: every stage has bounded
  in-flight work, dispatch favors the most-downstream runnable stage (the
  select_operator_to_run bias — finishing blocks closest to the output
  releases memory earliest), and blocks flow between stages as ObjectRefs
  without ever funneling through the driver.
- backpressure is a BYTE budget, not a CPU-count window: each stage's
  admission window is cfg.data_inflight_budget_bytes divided by a block
  size estimated from the first block of its input (sampled-uniform
  assumption; re-estimated as real blocks complete).
- actor pools autoscale in [min_size, max_size]: scale up one actor per
  loop tick while input is queued and every live actor is at its
  in-flight cap; actors idle past cfg.data_actor_idle_reap_s (above
  min_size) are reaped; dispatch prefers an actor on a node that already
  holds the input block (locality ranking via the head's object
  directory), tie-broken by least load.
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu.config import cfg


class ActorPoolStrategy:
    """compute= strategy for map_batches: an autoscaling pool of actor
    workers (reference: ray.data.ActorPoolStrategy / compute.py)."""

    def __init__(
        self,
        min_size: int = 1,
        max_size: Optional[int] = None,
        max_tasks_in_flight_per_actor: Optional[int] = None,
    ):
        if min_size < 1:
            raise ValueError("min_size must be >= 1")
        if max_size is not None and max_size < min_size:
            raise ValueError("max_size must be >= min_size")
        self.min_size = min_size
        self.max_size = max_size or max(min_size, min_size * 4)
        self.max_tasks_in_flight = (
            max_tasks_in_flight_per_actor
            or cfg.data_max_tasks_in_flight_per_actor
        )

    def __repr__(self) -> str:
        return f"ActorPoolStrategy({self.min_size}, {self.max_size})"


def actors(min_size: int = 1, max_size: Optional[int] = None) -> ActorPoolStrategy:
    """Shorthand: compute=actors(2, 8)."""
    return ActorPoolStrategy(min_size, max_size)


@dataclass
class TaskStage:
    """Fused chain of row/batch ops, one stateless remote task per block."""

    ops: List[tuple]
    num_cpus: Optional[float] = None
    max_concurrency: Optional[int] = None  # explicit concurrency= cap


@dataclass
class ActorStage:
    """One map_batches op executed on an autoscaling actor pool."""

    fn: Any  # callable or callable class
    kwargs: dict  # batch_size / batch_format / zero_copy
    pool: ActorPoolStrategy = field(default_factory=ActorPoolStrategy)
    num_cpus: Optional[float] = None
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: dict = field(default_factory=dict)


class _BatchWorker:
    """Actor-pool map worker: instantiates a callable-class UDF once and
    applies it per block (actor_pool_map_operator's _MapWorker)."""

    def __init__(self, fn_ser: bytes, ctor_args: tuple, ctor_kwargs: dict):
        fn = cloudpickle.loads(fn_ser)
        self._fn = (
            fn(*ctor_args, **ctor_kwargs) if isinstance(fn, type) else fn
        )

    def ready(self) -> bool:
        return True

    def apply(self, op_kwargs: dict, block: List[Any]) -> List[Any]:
        from .dataset import _apply_batches

        return _apply_batches(self._fn, block, op_kwargs)


class _PoolActor:
    __slots__ = ("handle", "node_id", "ongoing", "idle_since")

    def __init__(self, handle, node_id):
        self.handle = handle
        self.node_id = node_id
        self.ongoing = 0
        self.idle_since = time.monotonic()


class _ActorPool:
    """Driver-side pool state for one ActorStage."""

    def __init__(self, stage: ActorStage, rt):
        self._stage = stage
        self._rt = rt
        # UDFs defined in driver scripts/tests aren't importable on
        # workers: register their module for by-value pickling first
        # (same treatment task/actor submission applies to user code)
        from ray_tpu.cluster.client import _ship_module_by_value

        _ship_module_by_value(stage.fn)
        self._fn_ser = cloudpickle.dumps(stage.fn)
        self.actors: List[_PoolActor] = []
        for _ in range(stage.pool.min_size):
            self._spawn()

    def _spawn(self) -> None:
        opts: dict = {"max_restarts": 1}
        if self._stage.num_cpus is not None:
            opts["num_cpus"] = self._stage.num_cpus
        handle = (
            ray_tpu.remote(_BatchWorker)
            .options(**opts)
            .remote(
                self._fn_ser,
                self._stage.fn_constructor_args,
                self._stage.fn_constructor_kwargs,
            )
        )
        node_id = None
        loc = getattr(self._rt, "actor_location", None)
        if loc is not None:
            try:
                node_id, _ = loc(handle._actor_id)
            except Exception:  # noqa: BLE001
                node_id = None
        self.actors.append(_PoolActor(handle, node_id))

    @property
    def size(self) -> int:
        return len(self.actors)

    def has_capacity(self) -> bool:
        cap = self._stage.pool.max_tasks_in_flight
        return any(a.ongoing < cap for a in self.actors)

    def maybe_scale_up(self, queued: int) -> None:
        if (
            queued > 0
            and self.size < self._stage.pool.max_size
            and not self.has_capacity()
        ):
            self._spawn()

    def reap_idle(self) -> None:
        now = time.monotonic()
        reap_after = cfg.data_actor_idle_reap_s
        while self.size > self._stage.pool.min_size:
            victim = next(
                (
                    a
                    for a in self.actors
                    if a.ongoing == 0 and now - a.idle_since > reap_after
                ),
                None,
            )
            if victim is None:
                return
            self.actors.remove(victim)
            try:
                ray_tpu.kill(victim.handle)
            except Exception:  # noqa: BLE001
                pass

    def pick(self, block_locations: List[str]) -> Optional[_PoolActor]:
        """Locality-ranked pick (actor_pool_map_operator.py:380-429
        capability): among actors with capacity, prefer one whose node
        already holds the block; tie-break by least ongoing work."""
        cap = self._stage.pool.max_tasks_in_flight
        cands = [a for a in self.actors if a.ongoing < cap]
        if not cands:
            return None
        if block_locations:
            local = [a for a in cands if a.node_id in block_locations]
            if local:
                cands = local
        best = min(cands, key=lambda a: a.ongoing)
        # refresh unknown node ids lazily — but only when locality is in
        # play: host-resident blocks have no locations, and polling
        # WaitActor per pick for them was a measurable RPC storm during
        # pool ramp (pending actors answer slowly)
        if best.node_id is None and block_locations:
            loc = getattr(self._rt, "actor_location", None)
            if loc is not None:
                try:
                    best.node_id, _ = loc(best.handle._actor_id)
                except Exception:  # noqa: BLE001
                    pass
        return best

    def submit(self, actor: _PoolActor, op_kwargs: dict, block):
        actor.ongoing += 1
        return actor.handle.apply.remote(op_kwargs, block)

    def submit_window(
        self, actor: _PoolActor, op_kwargs: dict, blocks: List[Any]
    ) -> List[Any]:
        """Submit a window of blocks to ONE actor in one batched pass —
        rides the runtime's ordered submission batch (one bookkeeping
        lock + one channel wakeup per window instead of per block).
        Falls back to per-block submission on runtimes without the
        batch API (the in-process local runtime)."""
        batch = getattr(self._rt, "submit_actor_method_batch", None)
        actor.ongoing += len(blocks)
        if batch is None:
            return [
                actor.handle.apply.remote(op_kwargs, b) for b in blocks
            ]
        return batch(
            actor.handle._actor_id,
            "apply",
            [((op_kwargs, b), {}) for b in blocks],
        )

    def complete(self, actor: _PoolActor) -> None:
        actor.ongoing -= 1
        if actor.ongoing == 0:
            actor.idle_since = time.monotonic()

    def shutdown(self) -> None:
        for a in self.actors:
            try:
                ray_tpu.kill(a.handle)
            except Exception:  # noqa: BLE001
                pass
        self.actors.clear()


def _est_bytes(block: Any) -> int:
    """Cheap block-size estimate for the byte budget."""
    try:
        return max(1, len(cloudpickle.dumps(block)))
    except Exception:  # noqa: BLE001
        return 1 << 16


@dataclass
class _StageState:
    stage: Any  # TaskStage | ActorStage
    queue: Any = field(default_factory=deque)  # input blocks/refs
    in_flight: Dict[str, tuple] = field(default_factory=dict)  # hex -> meta
    pool: Optional[_ActorPool] = None
    est_block_bytes: Optional[int] = None
    # True once est came from a MEASURED block (seal size of a completed
    # output), not an inherited/seeded guess
    est_measured: bool = False
    sample_attempts: int = 0  # bounded retries when a measurement fails

    def window(self) -> int:
        """Byte-budget admission window (resource_manager.py:55 analog):
        budget / estimated block size, clamped to keep the pipeline both
        alive and bounded. Until a REAL size sample lands the window stays
        conservative — the old 64KiB default admitted 1024 in-flight
        multi-MB blocks, gigabytes past the budget (r4 advisor finding)."""
        if self.est_block_bytes is None:
            return 16
        w = int(cfg.data_inflight_budget_bytes // self.est_block_bytes)
        cap = 1024 if self.est_measured else 16
        return max(2, min(w, cap))


class StreamingExecutor:
    """Pull-based pipeline over the stage list; yields output blocks (or
    refs) in completion order."""

    def __init__(self, input_blocks: List[Any], stages: List[Any]):
        from ray_tpu.core.runtime import get_runtime

        self._rt = get_runtime()
        self._stages = [_StageState(s) for s in stages]
        if self._stages:
            self._stages[0].queue = deque(input_blocks)
            # byte-budget seed: sample the first host-resident block (ref
            # inputs start from the conservative default and inherit
            # estimates downstream)
            if input_blocks and not isinstance(
                input_blocks[0], ray_tpu.ObjectRef
            ):
                self._stages[0].est_block_bytes = _est_bytes(input_blocks[0])
                self._stages[0].est_measured = True
            elif input_blocks:
                # ObjectRef inputs: calibrate stage 0 from one input's
                # seal size (nothing downstream ever samples INTO stage
                # 0, which would otherwise sit at the conservative window
                # forever — a ~64x parallelism cap for small blocks)
                size = self._measure_block(input_blocks[0], fetch_timeout=0.5)
                if size:
                    self._stages[0].est_block_bytes = size
                    self._stages[0].est_measured = True
        for st in self._stages:
            if isinstance(st.stage, ActorStage):
                st.pool = _ActorPool(st.stage, self._rt)
        self._locations: Dict[str, List[str]] = {}
        # refs MINTED by this pipeline (stage outputs fed downstream):
        # owned exclusively by the executor, so the moment the consuming
        # task completes they are garbage — freed eagerly in batches so a
        # 50k-block run doesn't accrete dead blocks in the stores until
        # the Python GC happens to run
        self._intermediate: set = set()
        self._free_batch: List[ray_tpu.ObjectRef] = []

    def _note_consumed(self, block: Any) -> None:
        # Same semantics as dropping the executor's last ObjectRef (the
        # head runs the identical free cascade, lineage release included,
        # when the decref lands) — just eager and batched instead of
        # waiting on Python GC + the flusher. Downstream blocks whose
        # reconstruction would need a freed input were equally
        # unreconstructable under the drop-ref path.
        if (
            isinstance(block, ray_tpu.ObjectRef)
            and block.hex in self._intermediate
        ):
            self._intermediate.discard(block.hex)
            self._free_batch.append(block)

    def _flush_frees(self, force: bool = False) -> None:
        if not self._free_batch or (len(self._free_batch) < 64 and not force):
            return
        batch, self._free_batch = self._free_batch, []
        free = getattr(self._rt, "free_objects", None)
        if free is None:
            return
        try:
            free(batch)
        except Exception:  # noqa: BLE001 - GC is advisory
            pass

    def _measure_block(
        self, ref: ray_tpu.ObjectRef, fetch_timeout: float = 5.0
    ) -> int:
        """Real byte size of a completed block: seal size from the object
        directory. On a cluster runtime the directory answer is FINAL —
        the old fallback pulled the entire remote block to the driver
        just to size it (a multi-MB fetch per stage calibration); when
        the seal size is unknown the conservative window default stands.
        Only the in-process local runtime (no directory, objects already
        in this heap) still samples one pickle."""
        sizes_fn = getattr(self._rt, "object_sizes", None)
        if sizes_fn is not None:
            size = sizes_fn([ref]).get(ref.hex, 0)
            return int(size) if size else 0
        try:
            return _est_bytes(self._rt.get_object(ref, fetch_timeout))
        except Exception:  # noqa: BLE001
            return 0

    # ------------------------------------------------------------------
    def _locate(self, refs: List[ray_tpu.ObjectRef]) -> None:
        """Batch-resolve block locations for locality ranking (head object
        directory; no-op on the local runtime)."""
        fn = getattr(self._rt, "object_locations", None)
        if fn is None:
            return
        missing = [r for r in refs if r.hex not in self._locations]
        if not missing:
            return
        try:
            self._locations.update(fn(missing))
        except Exception:  # noqa: BLE001
            for r in missing:
                self._locations[r.hex] = []

    def _dispatch_one(self, si: int, st: _StageState) -> bool:
        block = st.queue[0]
        if isinstance(st.stage, TaskStage):
            from .dataset import _apply_chain

            opts = {}
            if st.stage.num_cpus is not None:
                opts["num_cpus"] = st.stage.num_cpus
            task = _apply_chain.options(**opts) if opts else _apply_chain
            ref = task.remote(block, st.stage.ops)
        else:
            return self._dispatch_actor_window(si, st, budget=1) > 0
        st.in_flight[ref.hex] = (ref, si, None, block)
        st.queue.popleft()
        return True

    def _dispatch_actor_window(
        self, si: int, st: _StageState, budget: int
    ) -> int:
        """Dispatch up to ``budget`` queued blocks onto pool actors, a
        per-actor WINDOW per submission batch: each window rides one
        batched submit (one channel wakeup / one pipelined message)
        instead of a per-block round through the submission path."""
        dispatched = 0
        cap = st.stage.pool.max_tasks_in_flight
        while st.queue and dispatched < budget:
            head = st.queue[0]
            locs = (
                self._locations.get(head.hex, [])
                if isinstance(head, ray_tpu.ObjectRef)
                else []
            )
            actor = st.pool.pick(locs)
            if actor is None:
                st.pool.maybe_scale_up(len(st.queue))
                break
            window = min(
                budget - dispatched, max(1, cap - actor.ongoing), len(st.queue)
            )
            blocks = [st.queue.popleft() for _ in range(window)]
            refs = st.pool.submit_window(actor, st.stage.kwargs, blocks)
            for ref, block in zip(refs, blocks):
                st.in_flight[ref.hex] = (ref, si, actor, block)
            dispatched += window
        return dispatched

    def _stage_capacity(self, st: _StageState) -> int:
        cap = st.window() - len(st.in_flight)
        if isinstance(st.stage, TaskStage) and st.stage.max_concurrency:
            cap = min(cap, st.stage.max_concurrency - len(st.in_flight))
        return cap

    def run(self) -> Iterator[ray_tpu.ObjectRef]:
        """Yields final-stage output refs as they complete."""
        stages = self._stages
        if not stages:
            return
        try:
            while True:
                # 1) dispatch, most-downstream stage first: finishing
                #    near-output blocks releases pipeline memory earliest
                for si in range(len(stages) - 1, -1, -1):
                    st = stages[si]
                    if st.pool is not None and st.queue:
                        refs = [
                            b
                            for b in itertools.islice(st.queue, 64)
                            if isinstance(b, ray_tpu.ObjectRef)
                        ]
                        self._locate(refs)
                    budget = self._stage_capacity(st)
                    if st.pool is not None:
                        if budget > 0:
                            self._dispatch_actor_window(si, st, budget)
                        st.pool.maybe_scale_up(len(st.queue))
                        st.pool.reap_idle()
                    else:
                        while st.queue and budget > 0:
                            if not self._dispatch_one(si, st):
                                break
                            budget -= 1
                all_inflight = [
                    meta[0]
                    for st in stages
                    for meta in st.in_flight.values()
                ]
                if not all_inflight:
                    if all(not st.queue for st in stages):
                        return
                    # queues non-empty but nothing dispatchable (pool
                    # saturated edge): brief yield, loop again
                    time.sleep(0.005)
                    continue
                # 2) wait for completions anywhere in the pipeline; after
                # the first is ready, sweep everything already completed
                # in the same pass (one dispatch scan amortizes over the
                # whole batch instead of one scan per block)
                ready, rest = ray_tpu.wait(
                    all_inflight,
                    num_returns=1,
                    timeout=1.0,
                )
                if ready and rest:
                    more, _ = ray_tpu.wait(
                        rest, num_returns=len(rest), timeout=0.0
                    )
                    ready = ready + more
                for ref in ready:
                    for si, st in enumerate(stages):
                        meta = st.in_flight.pop(ref.hex, None)
                        if meta is None:
                            continue
                        if meta[2] is not None:
                            st.pool.complete(meta[2])
                        # the consuming task is done with its input: an
                        # executor-owned intermediate block is garbage NOW
                        self._note_consumed(meta[3])
                        # calibrate the byte budget from the first MEASURED
                        # output of this stage (seal size from the
                        # directory; local fallback re-pickles one block) —
                        # the module's backpressure claim was previously
                        # seeded-only (r4 advisor finding). Measure only
                        # when a downstream stage still needs it; bounded
                        # retries when a measurement comes back empty.
                        tgt = (
                            stages[si + 1] if si + 1 < len(stages) else None
                        )
                        if (
                            tgt is not None
                            and not tgt.est_measured
                            and st.sample_attempts < 5
                        ):
                            st.sample_attempts += 1
                            size = self._measure_block(ref)
                            if size:
                                tgt.est_block_bytes = size
                                tgt.est_measured = True
                        nxt = si + 1
                        if nxt < len(stages):
                            # executor-minted ref flowing downstream: we
                            # are its only holder — eligible for the
                            # eager free once its consumer completes
                            self._intermediate.add(ref.hex)
                            stages[nxt].queue.append(ref)
                            if stages[nxt].est_block_bytes is None:
                                stages[nxt].est_block_bytes = (
                                    st.est_block_bytes
                                )
                        else:
                            yield ref
                        break
                self._flush_frees()
        finally:
            self._flush_frees(force=True)
            for st in stages:
                if st.pool is not None:
                    st.pool.shutdown()

    def run_refs(self) -> List[ray_tpu.ObjectRef]:
        return list(self.run())
