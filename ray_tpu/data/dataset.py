"""Lazy Dataset + streaming block executor."""
from __future__ import annotations

import builtins
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu


# -- block-level task (executed remotely) -----------------------------------


def _apply_batches(fn: Callable, block: Any, kwargs: dict):
    """One map_batches op over one block: slice into batches (zero-copy
    for Arrow blocks), convert to the requested batch_format, apply,
    convert back to a block (Arrow preferred for tabular results)."""
    from . import block as blk

    n = blk.block_len(block)
    size = kwargs.get("batch_size") or n or 1
    fmt = kwargs.get("batch_format") or "numpy"
    if blk.is_arrow(block):
        results = []
        for i in range(0, n, size):
            piece = blk.slice_block(block, i, min(size, n - i))
            results.append(
                blk.batch_to_block(fn(blk.arrow_to_batch(piece, fmt)))
            )
        if not results:
            return block
        if all(blk.is_arrow(r) for r in results):
            return blk.concat_blocks(results)
        out: List[Any] = []
        for r in results:
            out.extend(blk.block_rows(r))
        return out
    out = []
    for i in range(0, n, size):
        rows = block[i : i + size]
        scalar_rows = not (rows and isinstance(rows[0], dict))
        result = fn(_rows_to_batch(rows, fmt))
        out.extend(_batch_to_rows(result, unwrap_scalar=scalar_rows))
    return out


def _apply_chain_local(block: Any, ops: List[tuple]) -> Any:
    from . import block as blk

    for kind, fn, kwargs in ops:
        if kind != "map_batches" and blk.is_arrow(block):
            # row-wise ops see rows (block-accessor row view): one
            # materialization at the op boundary
            block = blk.block_rows(block)
        if kind == "map":
            block = [fn(row) for row in block]
        elif kind == "filter":
            block = [row for row in block if fn(row)]
        elif kind == "flat_map":
            block = [out for row in block for out in fn(row)]
        elif kind == "map_batches":
            block = _apply_batches(fn, block, kwargs)
    return block


_apply_chain = ray_tpu.remote(_apply_chain_local)

_BATCH_FORMATS = ("numpy", "default", "pandas", "pyarrow")


def _rows_to_batch(rows: List[Any], batch_format: str = "numpy"):
    """Batch conversion. "numpy"/"default": dict of numpy arrays (the
    reference's default); "pandas": a DataFrame; "pyarrow": a Table."""
    if batch_format == "pyarrow":
        from . import block as blk

        return blk.rows_to_arrow(rows)
    if batch_format == "pandas":
        import pandas as pd

        if rows and isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"data": list(rows)})
    if rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"data": np.asarray(rows)}


def _batch_to_rows(batch: Any, unwrap_scalar: bool = False) -> List[Any]:
    """``unwrap_scalar`` is set ONLY when the batch was built by wrapping
    NON-dict rows into a synthetic "data" column (_rows_to_batch): a real
    dataset whose rows are {"data": ...} dicts must keep its shape
    (matching block.py's metadata-marker discipline for Arrow blocks)."""
    from . import block as blk

    if blk.is_arrow(batch):
        return blk.block_rows(batch)
    if type(batch).__name__ == "DataFrame":  # pandas without the import
        return batch.to_dict("records")
    if isinstance(batch, dict):
        keys = list(batch.keys())
        n = len(batch[keys[0]])
        rows = [{k: batch[k][i] for k in keys} for i in range(n)]
        if unwrap_scalar and keys == ["data"]:
            return [r["data"] for r in rows]
        return rows
    return list(batch)


# -- dataset ----------------------------------------------------------------


class Dataset:
    """Lazy, immutable; transformations return new Datasets."""

    def __init__(self, input_blocks: List[Any], ops: List[tuple]):
        self._input_blocks = input_blocks  # host lists (lazy materialization)
        self._ops = ops

    # transformations (lazy)
    def map(self, fn: Callable) -> "Dataset":
        return Dataset(self._input_blocks, self._ops + [("map", fn, {})])

    def filter(self, fn: Callable) -> "Dataset":
        return Dataset(self._input_blocks, self._ops + [("filter", fn, {})])

    def flat_map(self, fn: Callable) -> "Dataset":
        return Dataset(self._input_blocks, self._ops + [("flat_map", fn, {})])

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        compute: Optional[Any] = None,
        concurrency: Optional[Any] = None,
        batch_format: str = "numpy",
        fn_constructor_args: Optional[tuple] = None,
        fn_constructor_kwargs: Optional[dict] = None,
        num_cpus: Optional[float] = None,
        **unknown,
    ) -> "Dataset":
        """Batch transform. Stateless callables run as fused block tasks;
        ``compute=ActorPoolStrategy(...)`` (or a tuple ``concurrency``,
        or a callable-class ``fn``) runs on an autoscaling actor pool with
        locality-ranked dispatch (execution.py). Unsupported arguments
        raise instead of being silently dropped."""
        if unknown:
            raise TypeError(
                f"map_batches got unsupported argument(s) "
                f"{sorted(unknown)}; supported: batch_size, compute, "
                "concurrency, batch_format, fn_constructor_args, "
                "fn_constructor_kwargs, num_cpus"
            )
        if batch_format not in _BATCH_FORMATS:
            raise ValueError(
                f"batch_format={batch_format!r} not supported "
                f"(one of {_BATCH_FORMATS})"
            )
        from .execution import ActorPoolStrategy

        pool: Optional[ActorPoolStrategy] = None
        task_cap: Optional[int] = None
        if isinstance(compute, ActorPoolStrategy):
            pool = compute
        elif compute is not None:
            raise TypeError(
                "compute must be an ActorPoolStrategy (or use "
                "concurrency=(min, max) for an autoscaling pool)"
            )
        if pool is not None and concurrency is not None:
            raise ValueError(
                "pass either compute=ActorPoolStrategy(...) or "
                "concurrency=, not both"
            )
        if isinstance(concurrency, tuple):
            pool = ActorPoolStrategy(*concurrency)
        elif isinstance(concurrency, int):
            if isinstance(fn, type):
                pool = ActorPoolStrategy(concurrency, concurrency)
            else:
                task_cap = concurrency
        if isinstance(fn, type) and pool is None:
            raise ValueError(
                "a callable-class UDF is stateful and must run on an "
                "actor pool: pass concurrency=n / (min, max) or "
                "compute=ActorPoolStrategy(...)"
            )
        op_kwargs = {"batch_size": batch_size, "batch_format": batch_format}
        if pool is not None:
            op = (
                "map_batches_actors",
                fn,
                {
                    **op_kwargs,
                    "pool": pool,
                    "num_cpus": num_cpus,
                    "fn_constructor_args": tuple(fn_constructor_args or ()),
                    "fn_constructor_kwargs": dict(fn_constructor_kwargs or {}),
                },
            )
        else:
            if fn_constructor_args or fn_constructor_kwargs:
                raise ValueError(
                    "fn_constructor_args/kwargs require an actor pool "
                    "(callable-class fn with concurrency/compute)"
                )
            op = (
                "map_batches",
                fn,
                {**op_kwargs, "num_cpus": num_cpus, "task_cap": task_cap},
            )
        return Dataset(self._input_blocks, self._ops + [op])

    def repartition(
        self,
        num_blocks: Optional[int] = None,
        *,
        target_block_bytes: Optional[int] = None,
    ) -> "Dataset":
        """Rebalance blocks. ``num_blocks``: all-to-all via the
        distributed shuffle (round-robin random partition; reference
        repartition exchange ops). ``target_block_bytes``: block-SIZE-
        aware local coalesce/split — adjacent blocks merge until the
        byte target (Arrow ``nbytes``; pickled estimate for row lists)
        and oversized blocks split, preserving row order (the
        reference's target-size block splitting)."""
        if (num_blocks is None) == (target_block_bytes is None):
            raise ValueError(
                "pass exactly one of num_blocks / target_block_bytes"
            )
        if num_blocks is not None:
            from .shuffle import shuffle_blocks

            refs = shuffle_blocks(
                self._executed_blocks(), num_blocks, mode="random", seed=0
            )
            return Dataset(refs, [])
        from . import block as blk

        out: List[Any] = []
        acc: List[Any] = []
        acc_bytes = 0
        for b in self.iter_blocks():
            n = blk.block_len(b)
            if n == 0:
                continue
            nbytes = blk.block_nbytes(b)
            if nbytes > target_block_bytes and n > 1:
                if acc:
                    out.append(blk.concat_blocks(acc))
                    acc, acc_bytes = [], 0
                per_row = max(1, nbytes // n)
                rows_per = max(1, int(target_block_bytes // per_row))
                for i in range(0, n, rows_per):
                    out.append(blk.slice_block(b, i, min(rows_per, n - i)))
                continue
            acc.append(b)
            acc_bytes += nbytes
            if acc_bytes >= target_block_bytes:
                out.append(blk.concat_blocks(acc))
                acc, acc_bytes = [], 0
        if acc:
            out.append(blk.concat_blocks(acc))
        return Dataset(out, [])

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Distributed two-stage random shuffle (hash-shuffle op analog):
        map tasks scatter rows to random partitions, reduce tasks gather —
        rows never funnel through the driver."""
        from .shuffle import shuffle_blocks

        num = max(1, len(self._input_blocks))
        # unseeded shuffles must differ call-to-call (epoch reshuffling)
        eff_seed = (
            seed
            if seed is not None
            else int(np.random.default_rng().integers(1 << 31))
        )
        from .shuffle import _reduce_shuffled

        refs = shuffle_blocks(
            self._executed_blocks(),
            num,
            mode="random",
            seed=eff_seed,
            reduce_fn=_reduce_shuffled,
            reduce_args=(eff_seed,),
        )
        return Dataset(refs, [])

    def sort(
        self,
        key: Optional[Any] = None,
        descending: bool = False,
    ) -> "Dataset":
        """Distributed sample sort: sample range bounds, range-partition,
        per-partition sorted reduce (sort_task_spec.py analog)."""
        from .shuffle import _reduce_sorted, sample_bounds, shuffle_blocks

        key_fn = _key_fn(key)
        blocks = self._executed_blocks()
        num = max(1, len(blocks))
        bounds = sample_bounds(blocks, num, key_fn)
        refs = shuffle_blocks(
            blocks,
            len(bounds) + 1,
            mode="range",
            key_fn=key_fn,
            bounds=bounds,
            reduce_fn=_reduce_sorted,
            reduce_args=(key_fn, descending),
            # sort's sampling stage already blocked the driver; the
            # streaming map emits each range partition as its own sealed
            # object (num_returns="streaming" block emission)
            streaming=True,
        )
        if descending:
            refs = refs[::-1]
        return Dataset(refs, [])

    def groupby(self, key: Any) -> "GroupedData":
        return GroupedData(self, key)

    def join(
        self,
        other: "Dataset",
        on: str,
        how: str = "inner",
        num_partitions: Optional[int] = None,
    ) -> "Dataset":
        """Distributed hash join (hash_shuffle join op analog): both sides
        hash-partition on the key; one join task per partition."""
        from .shuffle import shuffle_blocks

        key_fn = _key_fn(on)
        num = num_partitions or max(
            1, len(self._input_blocks), len(other._input_blocks)
        )
        left = shuffle_blocks(
            self._executed_blocks(), num, mode="hash", key_fn=key_fn
        )
        right = shuffle_blocks(
            other._executed_blocks(), num, mode="hash", key_fn=key_fn
        )
        refs = [
            _join_partition.remote(on, how, lp, rp)
            for lp, rp in zip(left, right)
        ]
        return Dataset(refs, [])

    def zip(self, other: "Dataset") -> "Dataset":
        rows_a, rows_b = self._materialize_rows(), other._materialize_rows()
        if len(rows_a) != len(rows_b):
            raise ValueError("zip requires datasets of equal row count")
        out = []
        for a, b in builtins.zip(rows_a, rows_b):
            row = dict(a) if isinstance(a, dict) else {"data": a}
            if isinstance(b, dict):
                for k, v in b.items():
                    row[k if k not in row else f"{k}_1"] = v
            else:
                row["data_1"] = b
            out.append(row)
        return from_items(out, override_num_blocks=len(self._input_blocks))

    def limit(self, n: int) -> "Dataset":
        return from_items(self.take(n), override_num_blocks=1)

    def unique(self, key: Optional[Any] = None) -> List[Any]:
        key_fn = _key_fn(key)
        seen, out = set(), []
        for row in self.iter_rows():
            k = key_fn(row) if key_fn else row
            marker = repr(k)
            if marker not in seen:
                seen.add(marker)
                out.append(k)
        return out

    # column ops (dict rows)
    def add_column(self, name: str, fn: Callable) -> "Dataset":
        return self.map(lambda row, _n=name, _f=fn: {**row, _n: _f(row)})

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self.map(
            lambda row, _c=tuple(cols): {
                k: v for k, v in row.items() if k not in _c
            }
        )

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self.map(
            lambda row, _c=tuple(cols): {k: row[k] for k in _c}
        )

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self.map(
            lambda row, _m=dict(mapping): {_m.get(k, k): v for k, v in row.items()}
        )

    # global aggregates (distributed partials, combined on the driver)
    def sum(self, on: Optional[str] = None):
        parts = self._block_aggregate("sum", on)
        return builtins.sum(p for p in parts if p is not None)

    def min(self, on: Optional[str] = None):
        parts = [p for p in self._block_aggregate("min", on) if p is not None]
        return builtins.min(parts) if parts else None

    def max(self, on: Optional[str] = None):
        parts = [p for p in self._block_aggregate("max", on) if p is not None]
        return builtins.max(parts) if parts else None

    def mean(self, on: Optional[str] = None):
        parts = [p for p in self._block_aggregate("moments", on) if p[0]]
        n = builtins.sum(p[0] for p in parts)
        return builtins.sum(p[1] for p in parts) / n if n else None

    def std(self, on: Optional[str] = None, ddof: int = 1):
        parts = [p for p in self._block_aggregate("moments", on) if p[0]]
        n = builtins.sum(p[0] for p in parts)
        if n <= ddof:
            return None
        total = builtins.sum(p[1] for p in parts)
        sq = builtins.sum(p[2] for p in parts)
        var = (sq - total * total / n) / (n - ddof)
        return float(np.sqrt(builtins.max(var, 0.0)))

    def _block_aggregate(self, agg: str, on: Optional[str]) -> List[Any]:
        if self._has_actor_stage():
            # actor stages can't fuse into the aggregate task: run the
            # pipeline to refs, then aggregate per block
            refs = [
                _block_agg.remote(b, [], agg, on)
                for b in self._executed_blocks()
            ]
        else:
            refs = [
                _block_agg.remote(b, self._ops, agg, on)
                for b in self._input_blocks
            ]
        return ray_tpu.get(refs)

    def _has_actor_stage(self) -> bool:
        return any(op[0] == "map_batches_actors" for op in self._ops)

    def _build_stages(self) -> List[Any]:
        """Compile the op list into executor stages: consecutive task ops
        fuse into one TaskStage; each actor map_batches is its own
        ActorStage (execution.py topology)."""
        from .execution import ActorStage, TaskStage

        stages: List[Any] = []
        cur: List[tuple] = []
        cur_cpus: Optional[float] = None
        cur_cap: Optional[int] = None

        def flush():
            nonlocal cur, cur_cpus, cur_cap
            if cur:
                stages.append(
                    TaskStage(cur, num_cpus=cur_cpus, max_concurrency=cur_cap)
                )
                cur, cur_cpus, cur_cap = [], None, None

        for kind, fn, kwargs in self._ops:
            if kind == "map_batches_actors":
                flush()
                stages.append(
                    ActorStage(
                        fn=fn,
                        kwargs={
                            "batch_size": kwargs.get("batch_size"),
                            "batch_format": kwargs.get("batch_format"),
                        },
                        pool=kwargs["pool"],
                        num_cpus=kwargs.get("num_cpus"),
                        fn_constructor_args=kwargs.get(
                            "fn_constructor_args", ()
                        ),
                        fn_constructor_kwargs=kwargs.get(
                            "fn_constructor_kwargs", {}
                        ),
                    )
                )
            else:
                cur.append((kind, fn, kwargs))
                if kwargs.get("num_cpus") is not None:
                    cur_cpus = max(cur_cpus or 0.0, kwargs["num_cpus"])
                if kwargs.get("task_cap") is not None:
                    cur_cap = (
                        kwargs["task_cap"]
                        if cur_cap is None
                        else min(cur_cap, kwargs["task_cap"])
                    )
        flush()
        return stages

    def _executed_blocks(self) -> List[Any]:
        """Apply pending ops, returning blocks as ObjectRefs — blocks stay
        in the object store end-to-end (streaming_executor.py:77
        semantics); nothing funnels through the driver. Host-list input
        blocks with no pending ops pass through as-is (they are already
        driver-resident; shipping them is the consumer's decision)."""
        if not self._ops:
            return list(self._input_blocks)
        from .execution import StreamingExecutor

        return StreamingExecutor(
            self._input_blocks, self._build_stages()
        ).run_refs()

    def union(self, other: "Dataset") -> "Dataset":
        """Concatenate block lists — no row materialization; each side's
        pending ops are submitted as block tasks and the refs carried
        over."""
        return Dataset(
            list(self._executed_blocks()) + list(other._executed_blocks()),
            [],
        )

    def split(self, n: int) -> List["Dataset"]:
        """Block-granularity split (the reference's equal=False default,
        dataset.py split): blocks stay refs. When there are fewer blocks
        than splits, fall back to row-level rebalancing."""
        if len(self._input_blocks) >= n:
            blocks = self._executed_blocks()
            return [
                Dataset([blocks[i] for i in idx], [])
                for idx in np.array_split(np.arange(len(blocks)), n)
            ]
        rows = self._materialize_rows()
        splits = np.array_split(np.arange(len(rows)), n)
        return [
            from_items([rows[i] for i in idx], override_num_blocks=1)
            for idx in splits
        ]

    # execution (streaming)
    def iter_blocks(self, *, prefetch_blocks: int = 0) -> Iterator[List[Any]]:
        """Streaming executor: the op plan compiles to a stage topology
        (task fusion + actor-pool stages) executed as a pipeline with a
        byte-budget admission window per stage (execution.py). Blocks may
        be host lists or ObjectRefs (shuffle outputs stay in the object
        store until consumed — the driver only materializes a block at
        its own consumption point, here).

        ``prefetch_blocks``: pull up to this many upcoming blocks over
        the object plane concurrently with the consumer (depth-N
        prefetch) — a reduce output that seals while the consumer is
        busy is already local by the time the iterator reaches it, so a
        training step overlaps shuffle tail latency instead of stalling
        per block."""
        if not self._ops:
            yield from _prefetched_blocks(
                iter(self._input_blocks), prefetch_blocks
            )
            return
        from .execution import StreamingExecutor

        yield from _prefetched_blocks(
            StreamingExecutor(self._input_blocks, self._build_stages()).run(),
            prefetch_blocks,
        )

    def iter_rows(self) -> Iterator[Any]:
        from . import block as blk

        for block in self.iter_blocks():
            yield from blk.rows_iter(block)

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        prefetch_batches: int = 0,
    ) -> Iterator[Any]:
        """Arrow blocks batch as zero-copy slices (a block boundary may
        yield a short batch); ndarray blocks slice their buffer
        (zero-copy views); row-list blocks buffer across blocks.

        ``prefetch_batches``: streaming-ingest depth, in BLOCKS — up to
        this many upcoming blocks are pulled over the object plane while
        the consumer processes the current one, overlapping fetch (and
        the shuffle's reduce tail) with the train step. 0 (default) =
        fully synchronous pulls; training dataset shards default to
        cfg.data_prefetch_batches (train/session.py DataIterator)."""
        from . import block as blk

        buf: List[Any] = []
        for block in self.iter_blocks(
            prefetch_blocks=max(0, int(prefetch_batches))
        ):
            if blk.is_arrow(block):
                if buf:
                    yield _rows_to_batch(buf, batch_format)
                    buf = []
                n = block.num_rows
                for i in range(0, n, batch_size):
                    piece = blk.slice_block(
                        block, i, min(batch_size, n - i)
                    )
                    yield blk.arrow_to_batch(piece, batch_format)
                continue
            if blk.is_ndarray(block):
                if buf:
                    yield _rows_to_batch(buf, batch_format)
                    buf = []
                for i in range(0, len(block), batch_size):
                    yield _ndarray_to_batch(
                        block[i : i + batch_size], batch_format
                    )
                continue
            for row in block:
                buf.append(row)
                if len(buf) >= batch_size:
                    yield _rows_to_batch(buf, batch_format)
                    buf = []
        if buf:
            yield _rows_to_batch(buf, batch_format)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(len(b) for b in self.iter_blocks())

    def materialize(self) -> "Dataset":
        """Execute pending ops; blocks land in the object store as refs
        (MaterializedDataset semantics — NOT a driver copy)."""
        return Dataset(self._executed_blocks(), [])

    def num_blocks(self) -> int:
        return len(self._input_blocks)

    def _materialize_rows(self) -> List[Any]:
        return self.take_all()

    def __repr__(self) -> str:
        return (
            f"Dataset(num_blocks={len(self._input_blocks)}, "
            f"num_ops={len(self._ops)})"
        )


def _resolve_block(b: Any) -> Any:
    return ray_tpu.get(b) if isinstance(b, ray_tpu.ObjectRef) else b


def _prefetched_blocks(block_iter: Iterator[Any], depth: int) -> Iterator[Any]:
    """Depth-N streaming consumption: keep up to ``depth`` upcoming
    blocks' object-plane pulls in flight while the consumer holds the
    current one. Results yield in ITERATOR order (a Dataset's block
    order is its row order); the pulls themselves overlap both each
    other and the consumer's step."""
    if depth <= 0:
        for b in block_iter:
            yield _resolve_block(b)
        return
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(
        max_workers=min(depth, 8), thread_name_prefix="data-prefetch"
    )
    try:
        window: Any = deque()
        for b in block_iter:
            window.append(pool.submit(_resolve_block, b))
            if len(window) > depth:
                yield window.popleft().result()
        while window:
            yield window.popleft().result()
    finally:
        # an abandoned iterator (consumer breaks out of its loop) must
        # not block on up-to-`depth` in-flight fetches nobody will read:
        # cancel queued pulls and return without joining — any running
        # pull drains in its pool thread
        pool.shutdown(wait=False, cancel_futures=True)


def _ndarray_to_batch(piece: np.ndarray, batch_format: str):
    """An ndarray block slice as a batch — the same shapes
    _rows_to_batch builds from scalar rows, without materializing rows
    ("numpy": a zero-copy {"data": view})."""
    if batch_format == "pyarrow":
        import pyarrow as pa

        from . import block as blk

        # pa.array only accepts 1-D input; multi-dim rows become list
        # rows (the same shape rows_to_arrow produced for them)
        arr = pa.array(piece if piece.ndim == 1 else list(piece))
        return blk.synthetic_table(arr, "data")
    if batch_format == "pandas":
        import pandas as pd

        return pd.DataFrame({"data": list(piece)})
    return {"data": piece}


def _key_fn(key: Any) -> Optional[Callable]:
    """None | column-name | callable -> row-key extractor."""
    if key is None or callable(key):
        return key
    return lambda row, _k=key: row[_k]


def _scalar(row: Any, on: Optional[str]) -> Any:
    return row[on] if on is not None else row


@ray_tpu.remote
def _block_agg(block: List[Any], ops: List[tuple], agg: str, on: Optional[str]):
    from . import block as blk

    block = blk.block_rows(_apply_chain_local(block, ops))
    values = [_scalar(r, on) for r in block]
    if agg == "sum":
        return builtins.sum(values) if values else None
    if agg == "min":
        return builtins.min(values) if values else None
    if agg == "max":
        return builtins.max(values) if values else None
    if agg == "moments":  # (count, sum, sum of squares)
        arr = np.asarray(values, dtype=np.float64)
        return (arr.size, float(arr.sum()), float((arr * arr).sum()))
    raise ValueError(agg)


@ray_tpu.remote
def _join_partition(on: str, how: str, left: List[Any], right: List[Any]):
    index: Dict[Any, List[dict]] = {}
    for row in right:
        index.setdefault(row[on], []).append(row)
    out: List[dict] = []
    matched_right = set()
    for row in left:
        matches = index.get(row[on], [])
        if matches:
            for m in matches:
                merged = dict(row)
                for k, v in m.items():
                    if k != on:
                        merged[k if k not in merged else f"{k}_right"] = v
                out.append(merged)
            matched_right.add(row[on])
        elif how in ("left", "outer"):
            out.append(dict(row))
    if how in ("right", "outer"):
        for key, rows in index.items():
            if key not in matched_right:
                out.extend(dict(r) for r in rows)
    return out


@ray_tpu.remote
def _group_partition(
    key_is_col: bool,
    key: Any,
    agg: str,
    on: Optional[str],
    fn: Optional[Callable],
    part: List[Any],
):
    key_fn = _key_fn(key)
    groups: Dict[Any, List[Any]] = {}
    for row in part:
        groups.setdefault(key_fn(row) if key_fn else row, []).append(row)
    out = []
    for gkey, rows in groups.items():
        if agg == "map_groups":
            out.extend(fn(rows))
            continue
        values = [_scalar(r, on) for r in rows]
        if agg == "count":
            stat = len(rows)
        elif agg == "sum":
            stat = builtins.sum(values)
        elif agg == "min":
            stat = builtins.min(values)
        elif agg == "max":
            stat = builtins.max(values)
        elif agg == "mean":
            stat = float(np.mean(np.asarray(values, dtype=np.float64)))
        else:
            raise ValueError(agg)
        name = f"{agg}({on})" if on else agg
        if key_is_col:
            out.append({key: gkey, name: stat})
        else:
            out.append({"key": gkey, name: stat})
    return out


class GroupedData:
    """Hash-partition by key, then per-partition group/aggregate
    (reference: Dataset.groupby -> hash aggregate ops)."""

    def __init__(self, ds: Dataset, key: Any):
        self._ds = ds
        self._key = key

    def _run(self, agg: str, on: Optional[str] = None, fn=None) -> Dataset:
        from .shuffle import shuffle_blocks

        blocks = self._ds._executed_blocks()
        num = max(1, len(blocks))
        parts = shuffle_blocks(
            blocks, num, mode="hash", key_fn=_key_fn(self._key)
        )
        refs = [
            _group_partition.remote(
                isinstance(self._key, str), self._key, agg, on, fn, p
            )
            for p in parts
        ]
        return Dataset(refs, [])

    def count(self) -> Dataset:
        return self._run("count")

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self._run("sum", on)

    def min(self, on: Optional[str] = None) -> Dataset:
        return self._run("min", on)

    def max(self, on: Optional[str] = None) -> Dataset:
        return self._run("max", on)

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self._run("mean", on)

    def map_groups(self, fn: Callable) -> Dataset:
        return self._run("map_groups", fn=fn)


def from_items(
    items: Sequence[Any], *, override_num_blocks: Optional[int] = None
) -> Dataset:
    items = list(items)
    n_blocks = override_num_blocks or min(
        max(1, len(items) // 1000 or 1), 200
    )
    idx = np.array_split(np.arange(len(items)), n_blocks)
    blocks = [[items[i] for i in part] for part in idx]
    return Dataset(blocks, [])


def range_(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return from_items(
        builtins.range(n), override_num_blocks=override_num_blocks
    )


def from_numpy(arr: np.ndarray, **kwargs) -> Dataset:
    return from_items(list(arr), **kwargs)


def from_numpy_blocks(
    arr: np.ndarray, *, override_num_blocks: Optional[int] = None
) -> Dataset:
    """Dataset over raw ndarray blocks (rows along axis 0) — the
    zero-copy shuffle path: blocks, map partitions, and reduce outputs
    stay buffer-backed arrays end-to-end, so their pickle-5 frames
    scatter-write straight into the shm arena at every seal and
    iter_batches serves zero-copy {"data": view} batches. Use
    ``io.from_numpy`` for the Arrow-table (named-column) form."""
    n_blocks = override_num_blocks or min(
        max(1, len(arr) // 65536 or 1), 200
    )
    return Dataset(
        [b for b in np.array_split(arr, n_blocks) if len(b)], []
    )
