"""Lazy Dataset + streaming block executor."""
from __future__ import annotations

import builtins
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu


# -- block-level task (executed remotely) -----------------------------------


@ray_tpu.remote
def _apply_chain(block: List[Any], ops: List[tuple]) -> List[Any]:
    for kind, fn, kwargs in ops:
        if kind == "map":
            block = [fn(row) for row in block]
        elif kind == "filter":
            block = [row for row in block if fn(row)]
        elif kind == "flat_map":
            block = [out for row in block for out in fn(row)]
        elif kind == "map_batches":
            size = kwargs.get("batch_size") or len(block) or 1
            out: List[Any] = []
            for i in range(0, len(block), size):
                batch = _rows_to_batch(block[i : i + size])
                result = fn(batch)
                out.extend(_batch_to_rows(result))
            block = out
    return block


def _rows_to_batch(rows: List[Any]) -> Dict[str, np.ndarray]:
    """numpy batch format (the reference's default batch_format="numpy")."""
    if rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return {"data": np.asarray(rows)}


def _batch_to_rows(batch: Any) -> List[Any]:
    if isinstance(batch, dict):
        keys = list(batch.keys())
        n = len(batch[keys[0]])
        rows = [{k: batch[k][i] for k in keys} for i in range(n)]
        # unwrap the synthetic "data" column
        if keys == ["data"]:
            return [r["data"] for r in rows]
        return rows
    return list(batch)


# -- dataset ----------------------------------------------------------------


class Dataset:
    """Lazy, immutable; transformations return new Datasets."""

    def __init__(self, input_blocks: List[Any], ops: List[tuple]):
        self._input_blocks = input_blocks  # host lists (lazy materialization)
        self._ops = ops

    # transformations (lazy)
    def map(self, fn: Callable) -> "Dataset":
        return Dataset(self._input_blocks, self._ops + [("map", fn, {})])

    def filter(self, fn: Callable) -> "Dataset":
        return Dataset(self._input_blocks, self._ops + [("filter", fn, {})])

    def flat_map(self, fn: Callable) -> "Dataset":
        return Dataset(self._input_blocks, self._ops + [("flat_map", fn, {})])

    def map_batches(
        self, fn: Callable, *, batch_size: Optional[int] = None, **_ignored
    ) -> "Dataset":
        return Dataset(
            self._input_blocks,
            self._ops + [("map_batches", fn, {"batch_size": batch_size})],
        )

    def repartition(self, num_blocks: int) -> "Dataset":
        rows = self._materialize_rows()
        return from_items(rows, override_num_blocks=num_blocks)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        rows = self._materialize_rows()
        rng = np.random.default_rng(seed)
        order = rng.permutation(len(rows))
        return from_items(
            [rows[i] for i in order], override_num_blocks=len(self._input_blocks)
        )

    def union(self, other: "Dataset") -> "Dataset":
        return from_items(
            self._materialize_rows() + other._materialize_rows(),
            override_num_blocks=len(self._input_blocks)
            + len(other._input_blocks),
        )

    def split(self, n: int) -> List["Dataset"]:
        rows = self._materialize_rows()
        splits = np.array_split(np.arange(len(rows)), n)
        return [
            from_items([rows[i] for i in idx], override_num_blocks=1)
            for idx in splits
        ]

    # execution (streaming)
    def iter_blocks(self) -> Iterator[List[Any]]:
        """Streaming executor: bounded in-flight block tasks (backpressure,
        resource_manager.py semantics collapsed to a window)."""
        if not self._ops:
            yield from self._input_blocks
            return
        max_in_flight = max(
            2, int(ray_tpu.cluster_resources().get("CPU", 4))
        )
        blocks = list(self._input_blocks)
        in_flight: List[Any] = []
        i = 0
        while i < len(blocks) or in_flight:
            while i < len(blocks) and len(in_flight) < max_in_flight:
                in_flight.append(_apply_chain.remote(blocks[i], self._ops))
                i += 1
            ready, in_flight = ray_tpu.wait(in_flight, num_returns=1)
            yield ray_tpu.get(ready[0])

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from block

    def iter_batches(
        self, *, batch_size: int = 256, batch_format: str = "numpy"
    ) -> Iterator[Dict[str, np.ndarray]]:
        buf: List[Any] = []
        for row in self.iter_rows():
            buf.append(row)
            if len(buf) >= batch_size:
                yield _rows_to_batch(buf)
                buf = []
        if buf:
            yield _rows_to_batch(buf)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        return sum(len(b) for b in self.iter_blocks())

    def materialize(self) -> "Dataset":
        return from_items(
            self.take_all(), override_num_blocks=len(self._input_blocks)
        )

    def num_blocks(self) -> int:
        return len(self._input_blocks)

    def _materialize_rows(self) -> List[Any]:
        return self.take_all()

    def __repr__(self) -> str:
        return (
            f"Dataset(num_blocks={len(self._input_blocks)}, "
            f"num_ops={len(self._ops)})"
        )


def from_items(
    items: Sequence[Any], *, override_num_blocks: Optional[int] = None
) -> Dataset:
    items = list(items)
    n_blocks = override_num_blocks or min(
        max(1, len(items) // 1000 or 1), 200
    )
    idx = np.array_split(np.arange(len(items)), n_blocks)
    blocks = [[items[i] for i in part] for part in idx]
    return Dataset(blocks, [])


def range_(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return from_items(
        builtins.range(n), override_num_blocks=override_num_blocks
    )


def from_numpy(arr: np.ndarray, **kwargs) -> Dataset:
    return from_items(list(arr), **kwargs)
