"""RDT — device-tensor transport between actors/tasks.

TPU-native rethink of the reference's RDT tier
(/root/reference/python/ray/experimental/rdt/nixl_tensor_transport.py,
gpu_object_manager/): the reference moves GPU buffers process-to-process
over NIXL/NCCL side channels. On TPU the transports that exist are:

1. **same process** — hand the ``jax.Array`` over by reference: zero
   copies, the buffer never moves (local-runtime compiled-DAG edges and
   direct returns already do this).
2. **cross process, same host** — one device the processes cannot share:
   the minimal path is device→host DMA into the *shared-memory arena*
   (no pickle, no socket), then host→device DMA on the consumer. This
   module implements that: raw dtype/shape header + buffer bytes staged
   zero-copy through the node's shm store / DAG ring.
3. **cross host** — ride the ICI/DCN mesh INSIDE jit: shard or permute
   with XLA collectives (``ray_tpu.ops``, ``collective``); a framework
   side channel cannot beat the compiler's own transfer engine, so RDT
   deliberately does not reinvent it (scaling-book recipe).

``put_tensor``/``get_tensor`` give the explicit API; the tensor codec is
also used by compiled-DAG shm edges so device arrays crossing a ring skip
cloudpickle entirely.
"""
from __future__ import annotations

import json
from typing import Any, Optional, Tuple

import numpy as np

import ray_tpu

_MAGIC = b"RDT1"


def _is_device_array(value: Any) -> bool:
    try:
        import jax

        return isinstance(value, jax.Array)
    except ImportError:  # pragma: no cover
        return False


def encode_tensor(value: Any) -> Optional[bytes]:
    """Raw wire form for jax/numpy arrays (None: not a tensor). One
    device→host DMA for jax arrays; numpy arrays encode without a copy of
    the payload beyond the write itself."""
    if _is_device_array(value):
        host = np.asarray(value)
        kind = "jax"
    elif type(value) is np.ndarray:  # subclasses (MaskedArray) need pickle
        host = value
        kind = "np"
    else:
        return None
    # only plain numeric/bool buffers: structured dtypes, object dtypes,
    # and datetime-ish kinds don't survive a raw name+bytes round trip
    d = host.dtype
    if d.names is not None or d.hasobject or d.kind not in "biufcV":
        return None
    if d.kind == "V" and d.name.startswith("void"):
        return None  # raw void blobs (e.g. structured leftovers)
    if not d.isnative:
        # dtype travels by NAME (no byte order): normalize to native first
        host = host.astype(d.newbyteorder("="))
    host = np.ascontiguousarray(host)
    # dtype by NAME: ml_dtypes types (bfloat16, float8_*) have no loadable
    # numpy .str form, but their names resolve via ml_dtypes on decode
    header = json.dumps(
        {"k": kind, "d": host.dtype.name, "s": list(host.shape)}
    ).encode()
    return _MAGIC + len(header).to_bytes(4, "little") + header + host.tobytes()


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def decode_tensor(data: bytes) -> Tuple[bool, Any]:
    """(is_tensor, value). jax tensors land back on the default device via
    one host→device DMA; numpy stays host-side."""
    if not data.startswith(_MAGIC):
        return False, None
    hlen = int.from_bytes(data[4:8], "little")
    meta = json.loads(data[8 : 8 + hlen])
    arr = np.frombuffer(
        data, dtype=_resolve_dtype(meta["d"]), offset=8 + hlen
    ).reshape(meta["s"])
    if meta["k"] == "jax":
        import jax

        return True, jax.device_put(arr)
    return True, arr.copy()  # writable, decoupled from the wire buffer


def put_tensor(value: Any) -> "ray_tpu.ObjectRef":
    """Stage a device/host tensor into the object plane with the raw codec
    (no pickle). Plain ``ray_tpu.put`` works too — this path skips the
    serializer and keeps dtype/shape as a 1-line header.

    Device-plane fast path: a sealable ``jax.Array`` skips this codec
    entirely and seals as a DEVICE FRAME (cluster/device_plane) — the
    encode here pays ``np.asarray`` + ``tobytes`` (a full host copy of
    the payload) where the device frame exports the buffer zero-copy on
    host-aliasing backends and lands back as a ``jax.Array`` with one
    ``device_put`` straight from the arriving arena view. The codec
    stays as the fallback for numpy arrays and a disabled plane."""
    from ray_tpu.cluster import device_plane as _dp

    if _dp.device_plane_enabled() and _dp.is_sealable_device_array(value):
        return ray_tpu.put(value)
    data = encode_tensor(value)
    if data is None:
        raise TypeError(f"put_tensor expects a jax or numpy array, got {type(value)}")
    return ray_tpu.put(_RdtBlob(data))


def get_tensor(ref: "ray_tpu.ObjectRef", timeout: Optional[float] = None) -> Any:
    from ray_tpu.cluster.device_plane import landing

    # explicit landing scope: rdt payloads are tensors by contract, so
    # this pull opts the socket fetch into the device landing zone
    # (stripes stream to HBM in flight) — generic gets don't
    with landing("device"):
        out = ray_tpu.get(ref, timeout=timeout)
    if isinstance(out, _RdtBlob):
        ok, value = decode_tensor(out.data)
        if ok:
            return value
    return out


class _RdtBlob:
    """Pickle-thin wrapper: the payload is already raw bytes, so pickling
    this object is a header + one memcpy (no element-wise serialization)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data

    def __reduce__(self):
        return (_RdtBlob, (self.data,))
