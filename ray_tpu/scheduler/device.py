"""Device-resident scheduler state: the live runtime's default placement path.

SURVEY §7.6 / VERDICT r1 item 1: the head (and the single-process runtime)
drain their pending-lease queues through the shape-grouped waterfall kernel
(`hybrid_schedule_shapes`, scheduler/hybrid.py) with the cluster resource
arrays kept resident on the scheduler device. Per round the host ships only

  - dirty availability rows (delta sync, donated-buffer scatter), and
  - the batch's unique demand shapes + per-request shape ids,

and reads back one int32 node row per request. Full re-uploads happen only
on topology changes (node add/remove, array growth) tracked by
``ClusterView.topo_version``.

Pipelined rounds (ISSUE 6): ``schedule_async`` dispatches the kernel and
starts an async device→host copy of the placement rows, returning a
``PendingRound`` handle; the avail chain means round N+1 can be
dispatched immediately — its kernel consumes round N's ``avail_out``
device buffer without waiting for N's readback to materialize on the
host (the data dependency alone sequences the rounds on device). ``scheduler/pipeline.py`` drains the handles on a completion
thread, so the blocking readback disappears from the dispatch path
entirely. ``schedule()`` (dispatch + immediate ``result()``) remains the
synchronous fallback (``RAY_TPU_SCHED_PIPELINE=0``).

Beyond the lease round, the same resident arrays and dirty-row protocol
now feed the other two scheduling consumers: the PG bundle kernels read
``resident_arrays()`` (no per-PG re-upload of the cluster matrices), and
the unpark estimator's per-shape slot counts come from one batched
``shape_slots`` dispatch. Repeatedly-unplaceable demand parks in an
on-device ring (one resident row per resource shape) and retries via a
count-driven kernel (``ring_schedule``) whose readback is per-node
placement counts — no demand matrix is ever re-uploaded for parked work.

Platform choice: ``RAY_TPU_SCHED_PLATFORM`` selects the backing XLA device
("cpu" default, "tpu"/"axon" to pin the real chip). The default is host XLA
because a centralized head runs sub-millisecond scheduling rounds: the same
compiled kernels dispatch in microseconds on the host backend, while a
tunneled TPU pays a multi-ms round-trip per readback. The TPU path is the
same code — ``bench.py`` drives it at 100k-request scale where the chip's
throughput dominates the transfer floor.

All shapes are bucketed (requests, unique shapes → next power of two; node
rows, resource columns → the ClusterView capacity arrays, which already grow
by doubling) so steady-state rounds hit the jit cache. A persistent XLA
compilation cache makes the first round of a fresh process cheap too, and
``prewarm()`` background-compiles the bucket grid so first-touch rounds
after a topology change stop paying the compile spike inline.

Reference semantics anchor: cluster_lease_manager.cc:196 (shape-queue drain),
hybrid_scheduling_policy.cc:96-181 (scoring), batched per SURVEY §7.6. The
reference's "prefer local node" tie-break (hybrid_scheduling_policy.cc:96)
is deliberately disabled here: placement is computed centrally, where no
node is "local"; a fixed prefer row would funnel every sub-threshold request
onto one node (VERDICT r1 weak-5).
"""
from __future__ import annotations

import atexit
import logging
import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from ray_tpu.util.metrics import Histogram as _MetricHistogram

logger = logging.getLogger(__name__)

_BIG = 1e18  # padding demand: larger than any node total → never placed

# Round-latency decomposition (satellite: sched_round_ms alone hid where a
# slow round spent its time). upload = dirty-row/ring pushes + demand
# device_puts (host-blocking); kernel = dispatch → computation-done as
# observed at harvest (exact in synchronous mode and whenever the pipeline
# is the bottleneck; an idle pipeline harvesting late overstates it);
# readback = host materialization of the async device→host copy.
SCHED_UPLOAD_MS = _MetricHistogram(
    "sched_upload_ms",
    "Per-round host→device sync cost: dirty-row scatter pushes + demand "
    "shape/id uploads, in ms.",
    boundaries=(0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 25, 100, 500),
)
SCHED_KERNEL_MS = _MetricHistogram(
    "sched_kernel_ms",
    "Per-round kernel latency (dispatch to computation-ready) in ms.",
    boundaries=(0.1, 0.25, 0.5, 1, 2, 5, 10, 25, 50, 100, 500, 5000),
)
SCHED_READBACK_MS = _MetricHistogram(
    "sched_readback_ms",
    "Per-round placement readback materialization cost in ms.",
    boundaries=(0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 25, 100, 500),
)


def device_scheduler_default() -> bool:
    """Default ON (VERDICT r1): the XLA kernels ARE the product scheduler;
    RAY_TPU_DEVICE_SCHEDULER=0/false/no/off selects the NumPy golden model
    (kept for differential testing)."""
    from ray_tpu.config import cfg

    return cfg.device_scheduler


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


def pad_scatter(rows: np.ndarray, vals: np.ndarray):
    """Bucket-pad a scatter-set's (rows, vals) by repeating row 0 — a
    duplicate scatter-set of one row with identical values is
    deterministic, and padding keeps the jit cache keyed on bucket sizes
    only. The ONE encoding of that invariant, shared by the avail delta
    path, the ring flush, and the autoscaler's DeltaBinPacker."""
    pad = _bucket(rows.shape[0], 1) - rows.shape[0]
    if pad:
        rows = np.concatenate([rows, np.repeat(rows[:1], pad)])
        vals = np.concatenate([vals, np.repeat(vals[:1], pad, axis=0)])
    return rows, vals


def elastic_pack_solve(
    avail: np.ndarray,
    shapes: np.ndarray,
    counts: np.ndarray,
    *,
    iters: int = 24,
):
    """One batched ``solve_pack_counts`` for the unified elasticity plane,
    with both axes bucket-padded (node rows with zero capacity, shape rows
    with zero count) so the jit cache stays keyed on bucket sizes only —
    tick latency must not pay a re-trace every time demand churn changes
    U or the fleet changes N. Returns host-side
    ``(placed f32[U], per_node f32[U, N])`` trimmed back to true sizes."""
    n, r = int(avail.shape[0]), int(avail.shape[1])
    u = int(shapes.shape[0])
    if n == 0 or u == 0:
        return (
            np.zeros((u,), dtype=np.float32),
            np.zeros((u, n), dtype=np.float32),
        )
    np_pad = _bucket(n) - n
    up_pad = _bucket(u) - u
    if np_pad:
        avail = np.concatenate(
            [avail, np.zeros((np_pad, r), dtype=np.float32)]
        )
    if up_pad:
        shapes = np.concatenate(
            [shapes, np.zeros((up_pad, r), dtype=np.float32)]
        )
        counts = np.concatenate(
            [counts, np.zeros((up_pad,), dtype=np.float32)]
        )
    from .binpack import solve_pack_counts

    res = solve_pack_counts(
        np.asarray(avail, dtype=np.float32),
        np.asarray(shapes, dtype=np.float32),
        np.asarray(counts, dtype=np.float32),
        iters=int(iters),
    )
    placed = np.asarray(res.placed)[:u]
    per_node = np.asarray(res.per_node)[:u, :n]
    return placed.astype(np.float32), per_node.astype(np.float32)


_cache_configured = False
_jitted = None
_jitted_lock = threading.Lock()

# Interpreter-exit guard for prewarm threads: a jit compile still running
# inside XLA's C++ thread pool while CPython tears down aborts the process
# with "terminate called without an active exception". The flag stops the
# warm loop between compiles; the join bounds how long exit waits for the
# one compile that may be mid-flight.
_shutting_down = False
_live_prewarms: list = []


def _drain_prewarms() -> None:
    global _shutting_down
    _shutting_down = True
    for t in list(_live_prewarms):
        t.join(timeout=30.0)


atexit.register(_drain_prewarms)


def _jitted_fns():
    """Process-wide jitted kernels: every DeviceSchedulerState (one per
    Runtime/HeadServer, and tests create many) must share one jit cache, or
    each instance re-traces and re-compiles identical programs."""
    global _jitted
    with _jitted_lock:
        if _jitted is None:
            import jax

            from .hybrid import (
                hybrid_schedule_shapes_multi_impl,
                ring_schedule_impl,
                shape_slots_impl,
            )

            # NO donation anywhere in the round chain: donating avail made
            # jax block each dispatch until the donated buffer's producer
            # (the previous round's kernel) finished — serializing dispatch
            # with execution and erasing the pipeline's overlap entirely.
            # Round ordering needs only the data dependency (round N+1's
            # avail input IS round N's avail_out); the cost of not reusing
            # the buffer in place is one f32[C,R] allocation per round
            # (~1 MB at 10k nodes) — noise next to the overlap it buys.
            kernel = jax.jit(
                hybrid_schedule_shapes_multi_impl,
                static_argnames=(
                    "spread_threshold", "weights", "preempt", "explain",
                ),
            )
            push = jax.jit(
                lambda avail, rows, vals: avail.at[rows].set(vals),
            )
            ring = jax.jit(
                ring_schedule_impl,
                static_argnames=("spread_threshold", "weights", "preempt"),
            )
            slots = jax.jit(shape_slots_impl)
            _jitted = (kernel, push, ring, slots)
        return _jitted


def score_weights_from_cfg():
    """The round kernels' multi-objective weights (hybrid.ScoreWeights)
    from config — static under jit, so a weight edit is a one-time
    recompile, not a per-round upload."""
    from ray_tpu.config import cfg

    from .hybrid import ScoreWeights

    return ScoreWeights(
        util=float(cfg.sched_w_util),
        het=float(cfg.sched_w_het),
        frag=float(cfg.sched_w_frag),
        starve=float(cfg.sched_w_starve),
        locality=float(cfg.sched_w_locality),
    )


def _configure_compile_cache() -> None:
    """Persistent XLA compile cache so fresh head processes reuse kernels."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    import jax

    from ray_tpu.config import cfg

    path = cfg.xla_cache
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        logger.debug("persistent compile cache unavailable", exc_info=True)


class LazyDeviceState:
    """Deferred DeviceSchedulerState construction with a bring-up timeout.

    XLA backend initialization can block indefinitely when an accelerator
    transport is unhealthy (e.g. a wedged TPU tunnel). The scheduler must
    degrade to the NumPy golden model instead of freezing the whole control
    plane: the first ``get()`` spawns the init in a daemon thread and waits
    up to ``RAY_TPU_SCHED_INIT_TIMEOUT_S`` (default 30s); on timeout the
    caller proceeds host-side, and if the backend ever does come up the
    next round adopts it."""

    def __init__(self, enabled: bool, timeout_s: Optional[float] = None):
        self.enabled = enabled
        if timeout_s is None:
            from ray_tpu.config import cfg

            timeout_s = cfg.sched_init_timeout_s
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._result: Optional[DeviceSchedulerState] = None
        self._deadline: Optional[float] = None
        self._warned = False

    def _init(self) -> None:
        try:
            self._result = DeviceSchedulerState()
        except Exception:  # noqa: BLE001 - backend broken: host fallback
            logger.exception("device scheduler init failed; host fallback")
            self.enabled = False

    def get(self) -> Optional["DeviceSchedulerState"]:
        if not self.enabled:
            return None
        if self._result is not None:
            return self._result
        import time

        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._init, name="sched-xla-init", daemon=True
                )
                self._thread.start()
                self._deadline = time.monotonic() + self.timeout_s
        remaining = self._deadline - time.monotonic()
        if remaining > 0:
            self._thread.join(timeout=remaining)
        if self._result is not None:
            return self._result
        if not self._warned:
            self._warned = True
            logger.warning(
                "XLA scheduler backend not up after %.0fs; scheduling on "
                "the host golden model until it appears",
                self.timeout_s,
            )
        return None  # adopt later if/when the init thread finishes


class PendingRound:
    """Handle to a dispatched scheduling round.

    The kernel is in flight (or done) on the device and an async
    device→host copy of the placement rows has been requested;
    ``result()`` blocks only on THIS round's completion — later rounds
    already dispatched keep executing behind it (avail chain).
    """

    __slots__ = (
        "_node", "_b", "_preempt", "_u", "_terms", "dispatched_at", "ctx",
    )

    def __init__(
        self, node, b: int, ctx=None, preempt=None, u: int = 0, terms=None
    ):
        self._node = node
        self._b = b
        self._preempt = preempt  # int32[U_pad] device, or None
        self._u = u              # real (unpadded) shape count
        self._terms = terms      # f32[B_pad, 5] device, or None
        self.dispatched_at = time.perf_counter()
        self.ctx = ctx  # opaque caller payload (e.g. the round's specs)

    def result(self) -> np.ndarray:
        """int32[B] node row per request (-1 = unplaceable now)."""
        node = self._node
        if node is None:
            raise RuntimeError("PendingRound.result() consumed twice")
        try:
            node.block_until_ready()
        except AttributeError:  # pragma: no cover - non-jax array fallback
            pass
        SCHED_KERNEL_MS.observe(
            (time.perf_counter() - self.dispatched_at) * 1e3
        )
        t0 = time.perf_counter()
        rows = np.asarray(node)[: self._b]
        SCHED_READBACK_MS.observe((time.perf_counter() - t0) * 1e3)
        self._node = None  # drop the device buffer eagerly
        return rows

    def preempt_rows(self) -> Optional[np.ndarray]:
        """int32[U] per-shape nominated victim node (-1 = none), or None
        when the round dispatched without preemption. Call after
        ``result()`` — the kernel has finished, so this materializes
        without a wait (it rode the same async host copy)."""
        p = self._preempt
        if p is None:
            return None
        self._preempt = None
        return np.asarray(p)[: self._u]

    def terms_rows(self) -> Optional[np.ndarray]:
        """f32[B, 5] per-request cost attribution (hybrid.TERM_NAMES
        order; zero rows for unplaced requests), or None when the round
        dispatched without explain. Like ``preempt_rows``: call after
        ``result()`` — it rode the same async host copy."""
        t = self._terms
        if t is None:
            return None
        self._terms = None
        return np.asarray(t)[: self._b]


class DeviceSchedulerState:
    """Resident mirror of a ClusterView on one XLA device + the jitted
    scheduling round.

    Sync protocol (host view stays canonical, fed by agent reports):
      - every host mutation of an availability row marks it dirty;
      - ``sync(view)`` pushes dirty rows (or everything when topo_version
        moved) before a round;
      - the kernel's in-round deductions live in the round's avail_out
        buffer, which becomes the resident avail; the host applies the
        same deductions to its mirror (marking those rows dirty), so the
        next sync is an idempotent overwrite and the two copies cannot
        silently diverge FROM EACH OTHER: whatever the host mirror holds
        is what lands on device. The mirror itself can be transiently
        stale vs reality while rounds are in flight — an agent report
        (``update_available``) that predates an undelivered round's
        grants re-pushes the pre-grant value until that round's
        completion re-applies its deduction; pipelining widens this
        window from sub-round to ``depth`` rounds. That staleness is the
        documented trust model (resources.py): a resulting over-grant is
        caught by the agents' exact grant-or-reject and respilled, and
        the next authoritative report overwrites the row either way.

    Thread contract: ``sync`` under the caller's view lock; ``_lock``
    serializes device-buffer swaps (dirty push, round dispatch, ring
    round) and is held only across the dispatch + swap — never across a
    readback (the pre-pipeline code blocked every concurrent sync/push on
    the running round's host materialization).
    """

    def __init__(self, platform: Optional[str] = None):
        import jax

        _configure_compile_cache()
        if platform is None:
            from ray_tpu.config import cfg

            platform = cfg.sched_platform
        try:
            self.device = jax.devices(platform)[0]
        except RuntimeError:
            logger.warning(
                "scheduler platform %r unavailable; falling back to cpu", platform
            )
            self.device = jax.devices("cpu")[0]
        self._jax = jax
        self._totals = None  # f32[C,R] device
        self._avail = None   # f32[C,R] device, donated through every round
        self._alive = None   # bool[C] device
        self._ntypes = None  # int32[C] device node-type ids
        self._thr = None     # f32[T,R] device per-type throughput factors
        self._synced_topo = -1
        self._seed = 0
        self._lock = threading.Lock()
        self._kernel, self._push, self._ring_kernel, self._slots_kernel = (
            _jitted_fns()
        )
        # delta-sync / round accounting, surfaced via QueryState("sched")
        self.stats: Dict[str, int] = {
            "full_syncs": 0,
            "delta_pushes": 0,
            "delta_rows": 0,
            "delta_rows_hwm": 0,
            "rounds": 0,
            "ring_rounds": 0,
            "prewarmed": 0,
        }
        # --- parked-demand ring (device-resident shapes) ---
        from ray_tpu.config import cfg

        self.ring_slots = max(0, int(cfg.sched_ring_slots))
        self._ring_rows: Optional[np.ndarray] = None   # host mirror [S,R]
        self._ring_dev = None                          # f32[S,R] device
        self._ring_keys: Dict[object, int] = {}        # shape key -> slot
        self._ring_free: list = list(range(self.ring_slots))
        self._ring_dirty: set = set()
        self._prewarm_thread: Optional[threading.Thread] = None

    # -- sync ----------------------------------------------------------

    def sync(self, view) -> None:
        """Bring the device mirror up to date. Caller holds the view lock."""
        t0 = time.perf_counter()
        with self._lock:
            if view.topo_version != self._synced_topo:
                self._full_sync(view)
            elif view.dirty_rows:
                self._push_dirty(view)
            else:
                return
        SCHED_UPLOAD_MS.observe((time.perf_counter() - t0) * 1e3)

    def _full_sync(self, view) -> None:
        put = self._jax.device_put
        self._totals = put(np.ascontiguousarray(view.totals), self.device)
        self._avail = put(np.ascontiguousarray(view.avail), self.device)
        self._alive = put(np.ascontiguousarray(view.alive), self.device)
        # heterogeneity inputs ride the same full-sync (type registration
        # bumps topo_version): node-type ids at node capacity, throughput
        # factors bucket-padded on the type axis with all-ones rows (no
        # node references a pad type, and the pad keeps the jit cache
        # keyed on bucket sizes)
        ntypes = getattr(view, "node_types", None)
        if ntypes is None:
            self._ntypes = put(
                np.zeros(view.totals.shape[0], dtype=np.int32), self.device
            )
            self._thr = put(
                np.ones((1, view.totals.shape[1]), dtype=np.float32),
                self.device,
            )
        else:
            self._ntypes = put(np.ascontiguousarray(ntypes), self.device)
            t = len(view.type_names)
            t_pad = _bucket(t, 1)
            thr = np.ones(
                (t_pad, view.totals.shape[1]), dtype=np.float32
            )
            thr[:t] = view.type_throughput[:t, : view.totals.shape[1]]
            self._thr = put(thr, self.device)
        self._synced_topo = view.topo_version
        view.dirty_rows.clear()
        self.stats["full_syncs"] += 1
        # resource-axis growth invalidates the resident ring rows too
        if self._ring_rows is not None and (
            self._ring_rows.shape[1] != view.totals.shape[1]
        ):
            widened = np.zeros(
                (self.ring_slots, view.totals.shape[1]), dtype=np.float32
            )
            widened[:, : self._ring_rows.shape[1]] = self._ring_rows
            self._ring_rows = widened
            self._ring_dev = None  # re-upload lazily at next ring round
        self.prewarm(view.totals.shape[0], view.totals.shape[1])

    def _scatter_push(self, dev, rows: np.ndarray, vals: np.ndarray):
        """Bucket-padded scatter-set of ``rows``/``vals`` into ``dev``
        (``pad_scatter`` invariant)."""
        rows, vals = pad_scatter(rows, vals)
        put = self._jax.device_put
        return self._push(dev, put(rows, self.device), put(vals, self.device))

    def _push_dirty(self, view) -> None:
        rows = np.fromiter(view.dirty_rows, dtype=np.int32)
        view.dirty_rows.clear()
        vals = view.avail[rows].copy()
        self.stats["delta_pushes"] += 1
        self.stats["delta_rows"] += int(rows.shape[0])
        # high-water mark: the largest single delta push — a growing HWM
        # (→ node count) means the delta protocol has degraded to
        # full-matrix traffic and autoscaler/report churn needs a look
        # (surfaced via head QueryState("sched"))
        if int(rows.shape[0]) > self.stats["delta_rows_hwm"]:
            self.stats["delta_rows_hwm"] = int(rows.shape[0])
        self._avail = self._scatter_push(self._avail, rows, vals)

    def invalidate(self) -> None:
        """Force the next sync() to full-upload from the host mirror.

        Failure-path escape hatch: a dispatched round's deductions are
        already committed to the resident avail (``avail_out`` swap at
        dispatch), so a round that DIES before its readback leaves
        phantom deductions on device that the host mirror (canonical)
        never applied — and the dirty-row delta path would never
        overwrite rows no host mutation touches. One full re-upload
        restores device == host; later in-flight rounds re-apply their
        own deductions through their completions as usual."""
        with self._lock:
            self._synced_topo = -1

    def resident_arrays(self):
        """(totals, avail, alive) device refs for read-only kernel
        consumers (PG bundle packing, autoscaler residual packing, slot
        estimation). Caller must have sync()ed under its view lock;
        deductions flow back through the host mirror's dirty rows,
        exactly like lease-round grants."""
        return self._totals, self._avail, self._alive

    # -- the scheduling round ------------------------------------------

    def schedule_async(
        self,
        demands: Optional[np.ndarray] = None,
        spread_threshold: float = 0.5,
        ctx=None,
        shapes=None,
        ages: Optional[np.ndarray] = None,
        weights=None,
        locality: Optional[np.ndarray] = None,
    ) -> PendingRound:
        """Dispatch a placement round without blocking on its readback.

        f32[B,R] demands → PendingRound whose ``result()`` yields int32[B]
        node rows (-1 = unplaceable now). The caller must have called
        sync() under its view lock; R must match the synced arrays'
        resource axis. The avail chain makes round ordering the dispatch
        order: a later round's kernel consumes this round's deducted
        availability even before anything is read back.

        ``shapes``: optional precomputed ``(shape_rows f32[U,R],
        shape_ids int32[B])`` dedupe (hardest-first order) — the head
        caches dense rows per resource shape, so steady rounds skip the
        O(B·R) ``np.unique`` pass here entirely. ``demands`` may then be
        None.

        ``ages``: optional f32[U] normalized wait-age per shape (rounds
        parked / sched_starve_rounds). Uploading ages arms preemption
        nomination (cfg.sched_preempt): ``PendingRound.preempt_rows()``
        then yields the per-shape victim-node nominations. ``weights``:
        hybrid.ScoreWeights override (default: the cfg knobs).

        ``locality``: optional f32[U, N'] per-shape per-node locality
        fraction (head._round_shapes: input bytes resident per node,
        row-normalized). Uploaded — and traced into the kernel — only
        when the resolved weights carry locality > 0, so the default
        config never pays the extra upload and keeps the pre-locality
        program byte-for-byte.
        """
        from ray_tpu.config import cfg

        r = self._totals.shape[1]
        if shapes is not None:
            shape_demands, shape_ids = shapes
        else:
            from .hybrid import dedupe_shapes

            assert demands.shape[1] == r, (demands.shape, r)
            shape_demands, shape_ids = dedupe_shapes(demands)
        b = shape_ids.shape[0]
        u = shape_demands.shape[0]
        assert shape_demands.shape[1] == r, (shape_demands.shape, r)
        if weights is None:
            weights = score_weights_from_cfg()
        preempt = bool(cfg.sched_preempt) and ages is not None
        explain = bool(cfg.sched_explain)

        u_pad = _bucket(u + 1, 2)
        b_pad = _bucket(b)
        sd = np.full((u_pad, r), _BIG, dtype=np.float32)
        sd[:u] = shape_demands
        sids = np.full(b_pad, u_pad - 1, dtype=np.int32)  # padding → BIG shape
        sids[:b] = shape_ids
        age_vec = np.zeros(u_pad, dtype=np.float32)
        if ages is not None:
            age_vec[:u] = ages

        put = self._jax.device_put
        t_up = time.perf_counter()
        sd_dev = put(sd, self.device)
        sids_dev = put(sids, self.device)
        ages_dev = put(age_vec, self.device)
        loc_dev = None
        if locality is not None and getattr(weights, "locality", 0.0):
            # pad shapes with zero rows (no locality data → neutral);
            # clip/zero-pad the node axis to the resident capacity so a
            # view growth between round prep and dispatch cannot feed
            # the kernel a mis-shaped matrix
            c = int(self._totals.shape[0])
            loc = np.zeros((u_pad, c), dtype=np.float32)
            nn = min(int(locality.shape[1]), c)
            loc[:u, :nn] = locality[:u, :nn]
            loc_dev = put(loc, self.device)
        SCHED_UPLOAD_MS.observe((time.perf_counter() - t_up) * 1e3)
        with self._lock:
            self._seed += 1
            self.stats["rounds"] += 1
            res = self._kernel(
                self._totals,
                self._avail,
                self._alive,
                self._ntypes,
                self._thr,
                sd_dev,
                sids_dev,
                ages_dev,
                np.uint32(self._seed & 0xFFFFFFFF),
                spread_threshold=spread_threshold,
                weights=weights,
                preempt=preempt,
                locality=loc_dev,
                explain=explain,
            )
            self._avail = res.avail_out
        node = res.node
        try:
            node.copy_to_host_async()
            if preempt:
                res.preempt_node.copy_to_host_async()
            if explain:
                res.terms.copy_to_host_async()
        except AttributeError:  # pragma: no cover - older jax arrays
            pass
        return PendingRound(
            node,
            b,
            ctx=ctx,
            preempt=res.preempt_node if preempt else None,
            u=u,
            terms=res.terms if explain else None,
        )

    def schedule(self, demands: np.ndarray, spread_threshold: float = 0.5):
        """Synchronous round: dispatch + immediate readback (the
        RAY_TPU_SCHED_PIPELINE=0 path, and the single-process runtime)."""
        return self.schedule_async(demands, spread_threshold).result()

    # -- parked-demand ring --------------------------------------------

    def ring_park(self, key, dense_row: np.ndarray) -> bool:
        """Pin a resource shape in the on-device ring. Idempotent per key;
        returns False when the ring is full (caller falls back to the
        re-upload path for that shape)."""
        if self.ring_slots <= 0:
            return False
        with self._lock:
            if key in self._ring_keys:
                return True
            if not self._ring_free:
                return False
            r = self._totals.shape[1] if self._totals is not None else None
            if r is None or dense_row.shape[0] != r:
                return False
            if self._ring_rows is None or self._ring_rows.shape[1] != r:
                self._ring_rows = np.zeros(
                    (self.ring_slots, r), dtype=np.float32
                )
                self._ring_dev = None
            slot = self._ring_free.pop()
            self._ring_keys[key] = slot
            self._ring_rows[slot] = dense_row
            self._ring_dirty.add(slot)
            return True

    def ring_drop(self, key) -> None:
        """Release a shape's ring slot (its parked queue drained)."""
        with self._lock:
            slot = self._ring_keys.pop(key, None)
            if slot is not None:
                self._ring_rows[slot] = 0.0
                self._ring_dirty.add(slot)
                self._ring_free.append(slot)

    def ring_occupancy(self) -> int:
        return len(self._ring_keys)

    def ring_keys(self) -> list:
        """Snapshot of the currently-pinned shape keys (for the head's
        parked-set reconciliation sweep)."""
        with self._lock:
            return list(self._ring_keys)

    def ring_slot_of(self, key) -> Optional[int]:
        return self._ring_keys.get(key)

    def _ring_flush_locked(self) -> None:
        """Upload dirty ring rows (scatter, bucketed like avail pushes).
        Caller holds self._lock."""
        put = self._jax.device_put
        if self._ring_dev is None:
            if self._ring_rows is None:
                self._ring_rows = np.zeros(
                    (self.ring_slots, self._totals.shape[1]), dtype=np.float32
                )
            self._ring_dev = put(self._ring_rows, self.device)
            self._ring_dirty.clear()
            return
        if not self._ring_dirty:
            return
        rows = np.fromiter(self._ring_dirty, dtype=np.int32)
        self._ring_dirty.clear()
        vals = self._ring_rows[rows].copy()
        self._ring_dev = self._scatter_push(self._ring_dev, rows, vals)

    def ring_schedule(
        self,
        counts_by_slot: Dict[int, int],
        spread_threshold: float = 0.5,
        ages_by_slot: Optional[Dict[int, float]] = None,
        weights=None,
    ):
        """Place parked demand straight from the resident ring.

        ``counts_by_slot``: pending request count per ring slot. Returns
        (placed int64[S], per_node int32[S,N], preempt int32[S]) — the
        caller assigns its FIFO-parked specs rank-by-rank across
        ``per_node`` and leaves the remainder parked; ``preempt`` carries
        per-slot victim-node nominations (-1 = none) when
        ``ages_by_slot`` was supplied and preemption is on. Only the
        count (and age) vectors (S values) cross the host→device
        boundary; the shapes are already resident.
        """
        from ray_tpu.config import cfg

        t_up = time.perf_counter()
        counts = np.zeros(self.ring_slots, dtype=np.int32)
        for slot, c in counts_by_slot.items():
            counts[slot] = min(int(c), np.iinfo(np.int32).max)
        ages = np.zeros(self.ring_slots, dtype=np.float32)
        if ages_by_slot:
            for slot, a in ages_by_slot.items():
                ages[slot] = float(a)
        if weights is None:
            weights = score_weights_from_cfg()
        preempt = bool(cfg.sched_preempt) and ages_by_slot is not None
        put = self._jax.device_put
        with self._lock:
            self._ring_flush_locked()
            counts_dev = put(counts, self.device)
            ages_dev = put(ages, self.device)
            SCHED_UPLOAD_MS.observe((time.perf_counter() - t_up) * 1e3)
            self._seed += 1
            self.stats["ring_rounds"] += 1
            t_k = time.perf_counter()
            res = self._ring_kernel(
                self._totals,
                self._avail,
                self._alive,
                self._ntypes,
                self._thr,
                self._ring_dev,
                counts_dev,
                ages_dev,
                np.uint32(self._seed & 0xFFFFFFFF),
                spread_threshold=spread_threshold,
                weights=weights,
                preempt=preempt,
            )
            self._avail = res.avail_out
        placed = np.asarray(res.placed)
        per_node = np.asarray(res.per_node)
        preempt_rows = np.asarray(res.preempt_node)
        SCHED_KERNEL_MS.observe((time.perf_counter() - t_k) * 1e3)
        return placed, per_node, preempt_rows

    # -- unpark slot estimation ----------------------------------------

    def shape_slots(self, shapes: np.ndarray) -> np.ndarray:
        """int64[S] grantable-slot estimate per demand shape, computed on
        the resident arrays (one dispatch replaces S host NumPy scans).
        Shapes are bucket-padded with _BIG rows (0 slots) for jit reuse."""
        s = shapes.shape[0]
        r = self._totals.shape[1]
        s_pad = _bucket(s, 1)
        mat = np.full((s_pad, r), _BIG, dtype=np.float32)
        mat[:s] = shapes
        with self._lock:
            res = self._slots_kernel(
                self._totals,
                self._avail,
                self._alive,
                self._jax.device_put(mat, self.device),
            )
        return np.asarray(res)[:s].astype(np.int64)

    # -- jit prewarm ----------------------------------------------------

    def prewarm(self, n_cap: int, r: int, spread_threshold: float = 0.5):
        """Background-compile the round kernel across the bucketed
        (batch, unique-shape) grid for the CURRENT array geometry, so the
        first real round at each size hits the jit (or persistent) cache
        instead of paying a multi-second trace+compile inside the
        scheduler loop. Idempotent per geometry; re-armed by _full_sync
        when the node-capacity axis grows. No-op while a warm thread for
        any geometry is still running (the persistent cache makes
        stragglers cheap)."""
        from ray_tpu.config import cfg

        if not cfg.sched_prewarm:
            return
        if self._prewarm_thread is not None and self._prewarm_thread.is_alive():
            return
        key = (n_cap, r)
        if getattr(self, "_prewarmed_geometry", None) == key:
            return
        self._prewarmed_geometry = key

        def _warm():
            try:
                max_b = _bucket(int(cfg.sched_max_batch))
                b_sizes, b = [], 8
                while b <= max_b:
                    b_sizes.append(b)
                    b *= 4  # every other bucket: 8,32,128,512,2048(,8192)
                if b_sizes[-1] != max_b:
                    b_sizes.append(max_b)
                totals = np.ones((n_cap, r), dtype=np.float32)
                avail = np.ones((n_cap, r), dtype=np.float32)
                alive = np.ones(n_cap, dtype=bool)
                put = self._jax.device_put
                dev_t = put(totals, self.device)
                dev_al = put(alive, self.device)
                # nothing donates the avail buffer anymore: one upload
                # serves the whole grid (was ~2.5 MB re-put per cell,
                # contending with real rounds' uploads after every
                # topology change)
                dev_av = put(avail, self.device)
                # warm the exact variant real rounds dispatch: current
                # weights, preemption armed iff the head will arm it,
                # type axis at the CURRENT resident bucket (weights and
                # preempt are static — another variant would compile a
                # program no round ever runs)
                weights = score_weights_from_cfg()
                preempt_flag = bool(cfg.sched_preempt)
                explain_flag = bool(cfg.sched_explain)
                t_pad = (
                    self._thr.shape[0] if self._thr is not None else 1
                )
                dev_nt = put(np.zeros(n_cap, dtype=np.int32), self.device)
                dev_thr = put(
                    np.ones((t_pad, r), dtype=np.float32), self.device
                )
                for u_pad in (2, 4, 8, 16):
                    sd = np.full((u_pad, r), _BIG, dtype=np.float32)
                    sd[0, 0] = 1.0
                    sd_dev = put(sd, self.device)
                    ages_dev = put(
                        np.zeros(u_pad, dtype=np.float32), self.device
                    )
                    for b_pad in b_sizes:
                        if _shutting_down:
                            return
                        sids = np.zeros(b_pad, dtype=np.int32)
                        res = self._kernel(
                            dev_t,
                            dev_av,
                            dev_al,
                            dev_nt,
                            dev_thr,
                            sd_dev,
                            put(sids, self.device),
                            ages_dev,
                            np.uint32(1),
                            spread_threshold=spread_threshold,
                            weights=weights,
                            preempt=preempt_flag,
                            explain=explain_flag,
                        )
                        res.node.block_until_ready()
                        self.stats["prewarmed"] += 1
            except Exception:  # noqa: BLE001 - warm-up is best-effort
                logger.debug("scheduler jit prewarm failed", exc_info=True)
            finally:
                try:
                    _live_prewarms.remove(threading.current_thread())
                except ValueError:  # pragma: no cover
                    pass

        self._prewarm_thread = threading.Thread(
            target=_warm, name="sched-prewarm", daemon=True
        )
        _live_prewarms.append(self._prewarm_thread)
        self._prewarm_thread.start()
