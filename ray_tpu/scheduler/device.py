"""Device-resident scheduler state: the live runtime's default placement path.

SURVEY §7.6 / VERDICT r1 item 1: the head (and the single-process runtime)
drain their pending-lease queues through the shape-grouped waterfall kernel
(`hybrid_schedule_shapes`, scheduler/hybrid.py) with the cluster resource
arrays kept resident on the scheduler device. Per round the host ships only

  - dirty availability rows (delta sync, donated-buffer scatter), and
  - the batch's unique demand shapes + per-request shape ids,

and reads back one int32 node row per request. Full re-uploads happen only
on topology changes (node add/remove, array growth) tracked by
``ClusterView.topo_version``.

Platform choice: ``RAY_TPU_SCHED_PLATFORM`` selects the backing XLA device
("cpu" default, "tpu"/"axon" to pin the real chip). The default is host XLA
because a centralized head runs sub-millisecond scheduling rounds: the same
compiled kernels dispatch in microseconds on the host backend, while a
tunneled TPU pays a multi-ms round-trip per readback. The TPU path is the
same code — ``bench.py`` drives it at 100k-request scale where the chip's
throughput dominates the transfer floor.

All shapes are bucketed (requests, unique shapes → next power of two; node
rows, resource columns → the ClusterView capacity arrays, which already grow
by doubling) so steady-state rounds hit the jit cache. A persistent XLA
compilation cache makes the first round of a fresh process cheap too.

Reference semantics anchor: cluster_lease_manager.cc:196 (shape-queue drain),
hybrid_scheduling_policy.cc:96-181 (scoring), batched per SURVEY §7.6. The
reference's "prefer local node" tie-break (hybrid_scheduling_policy.cc:96)
is deliberately disabled here: placement is computed centrally, where no
node is "local"; a fixed prefer row would funnel every sub-threshold request
onto one node (VERDICT r1 weak-5).
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_BIG = 1e18  # padding demand: larger than any node total → never placed


def device_scheduler_default() -> bool:
    """Default ON (VERDICT r1): the XLA kernels ARE the product scheduler;
    RAY_TPU_DEVICE_SCHEDULER=0/false/no/off selects the NumPy golden model
    (kept for differential testing)."""
    from ray_tpu.config import cfg

    return cfg.device_scheduler


def _bucket(n: int, floor: int = 8) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


_cache_configured = False
_jitted = None
_jitted_lock = threading.Lock()


def _jitted_fns():
    """Process-wide jitted kernels: every DeviceSchedulerState (one per
    Runtime/HeadServer, and tests create many) must share one jit cache, or
    each instance re-traces and re-compiles identical programs."""
    global _jitted
    with _jitted_lock:
        if _jitted is None:
            import jax

            from .hybrid import hybrid_schedule_shapes_impl

            kernel = jax.jit(
                hybrid_schedule_shapes_impl,
                static_argnames=("spread_threshold",),
                donate_argnums=(1,),  # avail: consumed, avail_out replaces it
            )
            push = jax.jit(
                lambda avail, rows, vals: avail.at[rows].set(vals),
                donate_argnums=(0,),
            )
            _jitted = (kernel, push)
        return _jitted


def _configure_compile_cache() -> None:
    """Persistent XLA compile cache so fresh head processes reuse kernels."""
    global _cache_configured
    if _cache_configured:
        return
    _cache_configured = True
    import jax

    from ray_tpu.config import cfg

    path = cfg.xla_cache
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        logger.debug("persistent compile cache unavailable", exc_info=True)


class LazyDeviceState:
    """Deferred DeviceSchedulerState construction with a bring-up timeout.

    XLA backend initialization can block indefinitely when an accelerator
    transport is unhealthy (e.g. a wedged TPU tunnel). The scheduler must
    degrade to the NumPy golden model instead of freezing the whole control
    plane: the first ``get()`` spawns the init in a daemon thread and waits
    up to ``RAY_TPU_SCHED_INIT_TIMEOUT_S`` (default 30s); on timeout the
    caller proceeds host-side, and if the backend ever does come up the
    next round adopts it."""

    def __init__(self, enabled: bool, timeout_s: Optional[float] = None):
        self.enabled = enabled
        if timeout_s is None:
            from ray_tpu.config import cfg

            timeout_s = cfg.sched_init_timeout_s
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._result: Optional[DeviceSchedulerState] = None
        self._deadline: Optional[float] = None
        self._warned = False

    def _init(self) -> None:
        try:
            self._result = DeviceSchedulerState()
        except Exception:  # noqa: BLE001 - backend broken: host fallback
            logger.exception("device scheduler init failed; host fallback")
            self.enabled = False

    def get(self) -> Optional["DeviceSchedulerState"]:
        if not self.enabled:
            return None
        if self._result is not None:
            return self._result
        import time

        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._init, name="sched-xla-init", daemon=True
                )
                self._thread.start()
                self._deadline = time.monotonic() + self.timeout_s
        remaining = self._deadline - time.monotonic()
        if remaining > 0:
            self._thread.join(timeout=remaining)
        if self._result is not None:
            return self._result
        if not self._warned:
            self._warned = True
            logger.warning(
                "XLA scheduler backend not up after %.0fs; scheduling on "
                "the host golden model until it appears",
                self.timeout_s,
            )
        return None  # adopt later if/when the init thread finishes


class DeviceSchedulerState:
    """Resident mirror of a ClusterView on one XLA device + the jitted
    scheduling round.

    Sync protocol (host view stays canonical, fed by agent reports):
      - every host mutation of an availability row marks it dirty;
      - ``sync(view)`` pushes dirty rows (or everything when topo_version
        moved) before a round;
      - the kernel's in-round deductions live in the donated avail buffer;
        the host applies the same deductions to its mirror (marking those
        rows dirty), so the next sync is an idempotent overwrite and the
        two copies can never silently diverge.
    """

    def __init__(self, platform: Optional[str] = None):
        import jax

        _configure_compile_cache()
        if platform is None:
            from ray_tpu.config import cfg

            platform = cfg.sched_platform
        try:
            self.device = jax.devices(platform)[0]
        except RuntimeError:
            logger.warning(
                "scheduler platform %r unavailable; falling back to cpu", platform
            )
            self.device = jax.devices("cpu")[0]
        self._jax = jax
        self._totals = None  # f32[C,R] device
        self._avail = None   # f32[C,R] device, donated through every round
        self._alive = None   # bool[C] device
        self._synced_topo = -1
        self._seed = 0
        self._lock = threading.Lock()
        self._kernel, self._push = _jitted_fns()

    # -- sync ----------------------------------------------------------

    def sync(self, view) -> None:
        """Bring the device mirror up to date. Caller holds the view lock."""
        with self._lock:
            if view.topo_version != self._synced_topo:
                self._full_sync(view)
            elif view.dirty_rows:
                self._push_dirty(view)

    def _full_sync(self, view) -> None:
        put = self._jax.device_put
        self._totals = put(np.ascontiguousarray(view.totals), self.device)
        self._avail = put(np.ascontiguousarray(view.avail), self.device)
        self._alive = put(np.ascontiguousarray(view.alive), self.device)
        self._synced_topo = view.topo_version
        view.dirty_rows.clear()

    def _push_dirty(self, view) -> None:
        rows = np.fromiter(view.dirty_rows, dtype=np.int32)
        view.dirty_rows.clear()
        vals = view.avail[rows].copy()
        pad = _bucket(rows.shape[0], 1) - rows.shape[0]
        if pad:
            # duplicate scatter-set of one row with identical values is
            # deterministic; keeps the jit cache keyed on bucket sizes only
            rows = np.concatenate([rows, np.repeat(rows[:1], pad)])
            vals = np.concatenate([vals, np.repeat(vals[:1], pad, axis=0)])
        put = self._jax.device_put
        self._avail = self._push(
            self._avail, put(rows, self.device), put(vals, self.device)
        )

    # -- the scheduling round ------------------------------------------

    def schedule(self, demands: np.ndarray, spread_threshold: float = 0.5):
        """Place a batch: f32[B,R] demands → int32[B] node rows (-1 =
        unplaceable now). The caller must have called sync() under its view
        lock; R must match the synced arrays' resource axis."""
        from .hybrid import dedupe_shapes

        b = demands.shape[0]
        r = self._totals.shape[1]
        assert demands.shape[1] == r, (demands.shape, r)
        shape_demands, shape_ids = dedupe_shapes(demands)

        u_pad = _bucket(shape_demands.shape[0] + 1, 2)
        b_pad = _bucket(b)
        sd = np.full((u_pad, r), _BIG, dtype=np.float32)
        sd[: shape_demands.shape[0]] = shape_demands
        sids = np.full(b_pad, u_pad - 1, dtype=np.int32)  # padding → BIG shape
        sids[:b] = shape_ids

        put = self._jax.device_put
        with self._lock:
            self._seed += 1
            res = self._kernel(
                self._totals,
                self._avail,
                self._alive,
                put(sd, self.device),
                put(sids, self.device),
                np.uint32(self._seed & 0xFFFFFFFF),
                spread_threshold=spread_threshold,
            )
            self._avail = res.avail_out
        return np.asarray(res.node)[:b]
