"""Simulated-scale scheduler harness: 10k nodes, up to 1M pending demands.

Drives the REAL head scheduling path — ``HeadServer`` with its scheduler
thread, fair batch popping, kernel rounds (pipelined or synchronous),
capacity-capped unparking, and the device-resident mirror — against a
synthetic topology with no agents and no RPC: nodes are injected straight
into the cluster view, and ``_send_grants`` is replaced by a local sink
that tallies delivered placements (the network boundary is exactly where
a simulated cluster stops being real, so that is the seam).

This is how the 10k-node × 1M-pending-task scale target (ROADMAP items
1/3) is measured reproducibly on any host: delivered placements/s
end-to-end through ``head._schedule_batch``, plus the round-latency
percentiles over the run's window. ``bench.py``'s ``sim_sched`` tier runs
it in both pipeline modes and publishes the ratio; tests run it small and
assert zero placement divergence between the modes on identical streams.

Health checking is inert by construction: a node that never appears in
``head._last_report`` reads as gap 0 (the agent-report liveness contract
starts at first report), so the synthetic nodes stay alive without a
reporter thread.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.util.metrics import percentile_from_buckets


#: the skewed stream's large shape: starvation-prone next to the
#: fractional-CPU mixture — a node must hold 16 contiguous free CPU
LARGE_SHAPE: Dict[str, float] = {"CPU": 16.0, "memory": 64.0}

#: heterogeneous node mix (fraction, type name, resources, throughput
#: factors): CPU-dense and highmem types next to the std baseline, with
#: Gavel-style relative throughput factors the het term consumes
NODE_MIX = (
    (0.6, "std", {"CPU": 64.0, "memory": 256.0}, None),
    (0.2, "dense", {"CPU": 128.0, "memory": 512.0},
     {"CPU": 1.25, "memory": 1.1}),
    (0.2, "highmem", {"CPU": 32.0, "memory": 1024.0},
     {"memory": 1.2, "CPU": 0.8}),
)


def build_demand_maps(
    num_demands: int,
    seed: int = 0,
    large_frac: float = 0.0,
    cpu_scale: float = 1.0,
) -> List[Dict[str, float]]:
    """The bench workload's CPU/memory mixture (bench.py build_demands),
    minus the TPU slice — the fill-once sim asserts full delivery, so
    every shape must be cluster-placeable. ``large_frac`` > 0 skews the
    stream with LARGE_SHAPE requests (doubled over the final fifth of
    the stream, so the tail arrives against an already-fragmented
    cluster); ``cpu_scale`` scales the small shapes up so a churn run
    (``hold_s``) can over-subscribe aggregate capacity — the
    fairness/fragmentation measurement workload."""
    rng = np.random.default_rng(seed)
    kind = rng.choice(3, num_demands, p=[0.70, 0.15, 0.15])
    s = float(cpu_scale)
    shapes = (
        {"CPU": 0.25 * s},
        {"CPU": 0.5 * s, "memory": 1.0 * s},
        {"CPU": 1.0 * s},
    )
    out = [dict(shapes[k]) for k in kind]
    if large_frac > 0:
        tail_start = int(num_demands * 0.8)
        p = rng.random(num_demands)
        for i in range(num_demands):
            frac = large_frac * (2.0 if i >= tail_start else 1.0)
            if p[i] < frac:
                out[i] = dict(LARGE_SHAPE)
    return out


def run_sim(
    num_nodes: int = 10_000,
    num_demands: int = 1_000_000,
    *,
    pipeline: bool = True,
    seed: int = 0,
    cpu_per_node: float = 64.0,
    memory_per_node: float = 256.0,
    collect_assignments: bool = False,
    timeout_s: float = 900.0,
    heterogeneous: bool = False,
    large_frac: float = 0.0,
    cpu_scale: float = 1.0,
    hold_rounds: int = 0,
) -> dict:
    """One sim run; returns delivered placements/s + round percentiles.

    ``pipeline`` toggles RAY_TPU_SCHED_PIPELINE for the run (restored
    after), selecting pipelined vs synchronous rounds through the exact
    production code path. All demands are enqueued under the head lock
    BEFORE the scheduler thread can pop, so two runs with the same seed
    see identical batch streams — the basis of the divergence check.

    ``heterogeneous`` builds the NODE_MIX topology (three node types
    with registered throughput factors) instead of a homogeneous fleet;
    ``large_frac`` skews the demand stream with LARGE_SHAPE requests and
    turns on the fairness/fragmentation measurements: per-large-spec
    wait in scheduling rounds past its queue-position arrival estimate
    (spec i's batch is popped at round ~i/sched_max_batch — a spec
    placed the round it is first scored waits ~0; parked specs
    accumulate), and a sampled stranded-capacity percentage — the share
    of the cluster's free CPU sitting on nodes that can no longer host
    LARGE_SHAPE.

    ``hold_rounds`` > 0 models task COMPLETIONS: every granted spec
    returns its capacity to the view once the round clock has advanced
    ``hold_rounds`` past its grant (a completer thread applies the
    returns like agent reports, dirty rows and all; round-based holds
    keep the return schedule comparable across modes on the same
    stream). This turns the fill-once sim into a steady-state churn
    benchmark where total demand may EXCEED cluster capacity — the
    regime where packing quality and starvation handling actually show
    up, since a fill-once run strands fragmented capacity permanently
    and measures only arrival order.
    """
    from ray_tpu.cluster.common import LeaseRequest, NodeInfo
    from ray_tpu.cluster.head import SCHED_ROUND_MS, HeadServer
    from ray_tpu.scheduler.resources import CPU

    env_before = os.environ.get("RAY_TPU_SCHED_PIPELINE")
    os.environ["RAY_TPU_SCHED_PIPELINE"] = "1" if pipeline else "0"
    head = None
    completer: Optional[threading.Thread] = None
    completer_stop = threading.Event()
    try:
        head = HeadServer(dashboard_port=None)
        delivered = 0
        assignments: Dict[str, str] = {}
        done = threading.Event()
        sink_lock = threading.Lock()
        large_grant_round: Dict[str, int] = {}
        large_ids: set = set()
        frag_samples: List[float] = []
        large_cpu = float(LARGE_SHAPE["CPU"])
        last_frag_round = -1
        # churn model: (due round, node row, summed demand row)
        pending_returns: deque = deque()

        def _round_clock() -> int:
            """Kernel rounds + ring retry rounds: parked work granted via
            the on-device ring advances this clock too."""
            ds = head._lazy_device._result
            return head.metrics["sched_rounds"] + (
                ds.stats["ring_rounds"] if ds is not None else 0
            )

        def _sample_frag() -> None:
            with head._lock:
                totals, avail, alive = head.view.active_arrays()
                free = avail[alive, CPU]
                cap = totals[alive, CPU]
            total_cpu = float(cap.sum())
            if total_cpu <= 0:
                return
            stranded = float(free[(free < large_cpu) & (free > 0)].sum())
            frag_samples.append(100.0 * stranded / total_cpu)

        def grant_sink(grants: Dict[str, List[LeaseRequest]]) -> None:
            nonlocal delivered, last_frag_round
            n = sum(len(v) for v in grants.values())
            rounds_now = _round_clock()
            with sink_lock:
                if collect_assignments:
                    for nid, specs in grants.items():
                        for s in specs:
                            assignments[s.task_id] = nid
                if large_ids:
                    for specs in grants.values():
                        for s in specs:
                            if s.task_id in large_ids:
                                large_grant_round[s.task_id] = rounds_now
                if large_frac > 0 and rounds_now != last_frag_round:
                    last_frag_round = rounds_now
                    _sample_frag()
                if hold_rounds > 0:
                    due = rounds_now + hold_rounds
                    width = head.view.totals.shape[1]
                    for nid, specs in grants.items():
                        row = head.view.row_of(nid)
                        d = np.zeros(width, dtype=np.float32)
                        for s in specs:
                            d[:] += head.vocab.pack(s.resources)[:width]
                        pending_returns.append((due, row, d))
                delivered += n
                if delivered >= num_demands:
                    done.set()

        head._send_grants = grant_sink

        def _completer() -> None:
            """Return held capacity like agent reports would: under the
            head lock, dirty rows marked, change counter bumped (which is
            what re-arms the parked-work retry path). Round-based due
            times: the ring retry rounds advance the clock even when the
            cluster is saturated, so returns always drain."""
            while not completer_stop.wait(0.02):
                clock = _round_clock()
                batch: List[tuple] = []
                with sink_lock:
                    while pending_returns and pending_returns[0][0] <= clock:
                        batch.append(pending_returns.popleft())
                if not batch:
                    continue
                with head._cond:
                    for _, row, d in batch:
                        head.view.add(row, d)
                    head._cond.notify_all()

        if hold_rounds > 0:
            completer = threading.Thread(
                target=_completer, name="sim-completer", daemon=True
            )
            completer.start()

        with head._cond:
            if heterogeneous:
                for _, tname, _, thr in NODE_MIX:
                    head.view.register_node_type(tname, thr)
                bounds = np.cumsum([m[0] for m in NODE_MIX])
                mix_rng = np.random.default_rng(seed + 1)
                picks = mix_rng.random(num_nodes)
                for i in range(num_nodes):
                    mi = int(np.searchsorted(bounds, picks[i]))
                    mi = min(mi, len(NODE_MIX) - 1)
                    _, tname, res, _ = NODE_MIX[mi]
                    nid = f"simnode-{i}"
                    head.nodes[nid] = NodeInfo(
                        node_id=nid, address="", resources=dict(res)
                    )
                    head.view.add_node(
                        nid, head.nodes[nid].resources, node_type=tname
                    )
            else:
                for i in range(num_nodes):
                    nid = f"simnode-{i}"
                    head.nodes[nid] = NodeInfo(
                        node_id=nid,
                        address="",
                        resources={
                            "CPU": cpu_per_node,
                            "memory": memory_per_node,
                        },
                    )
                    head.view.add_node(nid, head.nodes[nid].resources)

        demand_maps = build_demand_maps(
            num_demands, seed, large_frac, cpu_scale
        )
        specs = [
            LeaseRequest(
                task_id=f"sim-{i}",
                name="sim",
                payload=b"",
                return_ids=[],
                resources=res,
                max_retries=0,
            )
            for i, res in enumerate(demand_maps)
        ]
        large_arrival: Dict[str, int] = {}
        if large_frac > 0:
            from ray_tpu.config import cfg as _cfg

            max_batch = max(1, int(_cfg.sched_max_batch))
            for i, (s, res) in enumerate(zip(specs, demand_maps)):
                if res.get("CPU", 0.0) >= large_cpu:
                    large_ids.add(s.task_id)
                    # queue-position arrival estimate: the stream pops
                    # FIFO in MAX_BATCH rounds while the queue is deep
                    large_arrival[s.task_id] = i // max_batch

        round_buckets0 = SCHED_ROUND_MS.buckets_snapshot()
        t0 = time.perf_counter()
        with head._cond:
            head._pending.extend(specs)
            head._cond.notify_all()
        completed = done.wait(timeout=timeout_s)
        elapsed = time.perf_counter() - t0
        round_buckets1 = SCHED_ROUND_MS.buckets_snapshot()
        delta = [b1 - b0 for b0, b1 in zip(round_buckets0, round_buckets1)]

        ds = head._lazy_device._result
        out = {
            "pipeline": pipeline,
            "num_nodes": num_nodes,
            "num_demands": num_demands,
            "delivered": delivered,
            "completed": completed,
            "elapsed_s": round(elapsed, 3),
            "placements_per_s": round(delivered / elapsed, 1)
            if elapsed > 0
            else 0.0,
            "sched_round_p50_ms": round(
                percentile_from_buckets(
                    SCHED_ROUND_MS.boundaries, delta, 0.50
                ),
                3,
            ),
            "sched_round_p99_ms": round(
                percentile_from_buckets(
                    SCHED_ROUND_MS.boundaries, delta, 0.99
                ),
                3,
            ),
            "sched_rounds": int(sum(delta)),
            "device_stats": dict(ds.stats) if ds is not None else None,
            "pipeline_stats": (
                head._pipeline.stats() if head._pipeline is not None else None
            ),
            "ring_occupancy": ds.ring_occupancy() if ds is not None else 0,
        }
        if large_frac > 0:
            _sample_frag()  # final state, even if sampling never hit
            final_rounds = _round_clock()
            waits = [
                max(
                    0,
                    large_grant_round.get(t, final_rounds)
                    - large_arrival[t],
                )
                for t in large_ids
            ]
            out.update(
                {
                    "num_large": len(large_ids),
                    "large_delivered": len(large_grant_round),
                    "p50_wait_rounds_large": (
                        float(np.percentile(waits, 50)) if waits else 0.0
                    ),
                    "p99_wait_rounds_large": (
                        float(np.percentile(waits, 99)) if waits else 0.0
                    ),
                    # steady-state stranding: mean over the run's second
                    # half (the first half is mostly-empty cluster)
                    "fragmentation_pct": round(
                        float(
                            np.mean(
                                frag_samples[len(frag_samples) // 2:]
                            )
                        )
                        if frag_samples
                        else 0.0,
                        2,
                    ),
                    "fragmentation_pct_final": round(
                        frag_samples[-1] if frag_samples else 0.0, 2
                    ),
                    "preempt_nominations": head.metrics[
                        "preempt_nominations"
                    ],
                    "preemptions": head.metrics["preemptions"],
                }
            )
        if collect_assignments:
            out["assignments"] = assignments
        return out
    finally:
        completer_stop.set()
        if completer is not None:
            completer.join(timeout=2.0)
        if head is not None:
            head.shutdown(stop_agents=False)
        if env_before is None:
            os.environ.pop("RAY_TPU_SCHED_PIPELINE", None)
        else:
            os.environ["RAY_TPU_SCHED_PIPELINE"] = env_before


def run_sim_pair(
    num_nodes: int, num_demands: int, *, seed: int = 0, **kw
) -> dict:
    """Pipelined + synchronous runs over the SAME demand stream on the
    same host: the speedup ratio and the divergence count (both modes
    must place every spec, on identical nodes per spec when the stream
    is deterministic). This is the bench tier's workhorse.

    A throwaway warmup run at the same node geometry populates the
    process-wide jit cache first — without it the sync run (which goes
    first) pays every kernel compile and the comparison flatters the
    pipeline."""
    from ray_tpu.config import cfg

    warm_demands = min(num_demands, 3 * int(cfg.sched_max_batch))
    run_sim(num_nodes, warm_demands, pipeline=False, seed=seed, **kw)
    sync = run_sim(
        num_nodes, num_demands, pipeline=False, seed=seed,
        collect_assignments=True, **kw
    )
    piped = run_sim(
        num_nodes, num_demands, pipeline=True, seed=seed,
        collect_assignments=True, **kw
    )
    a_sync = sync.pop("assignments")
    a_piped = piped.pop("assignments")
    divergent = sum(
        1
        for tid, nid in a_sync.items()
        if a_piped.get(tid) != nid
    ) + sum(1 for tid in a_piped if tid not in a_sync)
    speedup = (
        piped["placements_per_s"] / sync["placements_per_s"]
        if sync["placements_per_s"]
        else 0.0
    )
    return {
        "sync": sync,
        "pipelined": piped,
        "placement_divergence": divergent,
        "pipeline_speedup": round(speedup, 2),
    }


def run_elasticity_sim(
    num_nodes: int = 10_000,
    *,
    ticks: int = 50,
    serve_tenants: int = 32,
    gangs: int = 8,
    task_shapes: int = 1000,
    seed: int = 0,
    cpu_per_node: float = 64.0,
    memory_per_node: float = 256.0,
) -> dict:
    """Controller-tick latency at sim scale (PR 19 perf claim): a real
    HeadServer with ``num_nodes`` synthetic nodes, serve pressure across
    ``serve_tenants`` tenants, ``gangs`` under-world gangs with declared
    wants, and ``task_shapes`` parked lease specs — then ``ticks``
    unified controller ticks, each one snapshot + ONE batched device
    solve + plan (actuation runs dry: no provider, retirement disabled).
    Returns assembly/solve tick percentiles — the number that replaces
    three Python control loops' worth of per-entity scanning."""
    from ray_tpu.cluster.common import LeaseRequest, NodeInfo
    from ray_tpu.cluster.head import HeadServer

    rng = np.random.default_rng(seed)
    saved = {
        k: os.environ.get(k)
        for k in (
            "RAY_TPU_ELASTIC_RETIRE_MAX",
            "RAY_TPU_ELASTIC_CONTROLLER",
        )
    }
    os.environ["RAY_TPU_ELASTIC_RETIRE_MAX"] = "0"
    # construct with the controller ticking OFF: the sim drives tick()
    # by hand so every tick is measured, none raced
    os.environ["RAY_TPU_ELASTIC_CONTROLLER"] = "0"
    head = None
    try:
        head = HeadServer(dashboard_port=None)
        head._send_grants = lambda grants: None
        with head._cond:
            for i in range(num_nodes):
                nid = f"simnode-{i}"
                head.nodes[nid] = NodeInfo(
                    node_id=nid,
                    address="",
                    resources={
                        "CPU": cpu_per_node,
                        "memory": memory_per_node,
                    },
                )
                head.view.add_node(nid, head.nodes[nid].resources)
            # serve pressure: one deployment, per-tenant waiting queues
            head._serve_budget["simdep"] = {
                "router-0": {
                    "usage": {},
                    "waiting": {},
                    "weights": {},
                    "pressure": {
                        f"tenant-{t}": {
                            "waiting": int(rng.integers(1, 64)),
                            "waiting_tokens": int(
                                rng.integers(256, 65536)
                            ),
                        }
                        for t in range(serve_tenants)
                    },
                    "ts": time.monotonic(),
                }
            }
            # gangs below their want: grow-back demand rows
            for g in range(gangs):
                world = int(rng.integers(1, 4))
                head._gangs[f"simgang-{g}"] = {
                    "epoch": 1,
                    "owner": "sim",
                    "members": {
                        r: f"simnode-{(g * 7 + r) % num_nodes}"
                        for r in range(world)
                    },
                    "min_size": 1,
                    "dead_ranks": [],
                    "updated": time.monotonic(),
                    "want_world": world + int(rng.integers(1, 5)),
                    "resources_per_rank": {"CPU": 4.0},
                    "grow": True,
                    "world_hint": None,
                }
            # parked task demand: shapes sized ABOVE per-node capacity so
            # the head's own scheduler loop keeps them infeasible across
            # every tick (feasible ones would drain into the grant sink)
            # — exactly the parked demand that drives provisioning
            for i in range(task_shapes):
                head._infeasible.append(
                    LeaseRequest(
                        task_id=f"simtask-{i}",
                        name="sim",
                        payload=b"",
                        return_ids=[],
                        resources={
                            "CPU": cpu_per_node
                            + 1.0
                            + float(rng.integers(0, 64)),
                            "memory": memory_per_node
                            + float(rng.integers(0, 256)),
                        },
                        max_retries=0,
                    )
                )
        ctrl = head._elasticity
        # untimed warmup ticks compile the padded solve program (and any
        # neighbor bucket the head's own infeasible-retry churn lands in)
        for _ in range(3):
            ctrl.tick()
        with ctrl._lock:
            ctrl._tick_ms.clear()
        t0 = time.perf_counter()
        for _ in range(ticks):
            ctrl.tick()
        elapsed = time.perf_counter() - t0
        pct = ctrl.tick_percentiles()
        last = ctrl.last_plan
        return {
            "num_nodes": num_nodes,
            "ticks": ticks,
            "elapsed_s": round(elapsed, 3),
            "ticks_per_s": round(ticks / elapsed, 2) if elapsed else 0.0,
            "tick_p50_ms": round(pct["p50_ms"], 3),
            "tick_p99_ms": round(pct["p99_ms"], 3),
            "demand_rows": last.demand_rows if last else 0,
            "solve_path": last.path if last else "none",
            "serve_hints": len(last.serve_hints) if last else 0,
            "world_hints": len(last.world_hints) if last else 0,
        }
    finally:
        if head is not None:
            head.shutdown(stop_agents=False)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


_WEIGHT_ENV = (
    ("RAY_TPU_SCHED_W_UTIL", "util"),
    ("RAY_TPU_SCHED_W_HET", "het"),
    ("RAY_TPU_SCHED_W_FRAG", "frag"),
    ("RAY_TPU_SCHED_W_STARVE", "starve"),
)


def _with_weights(weights: Tuple[float, float, float, float], fn):
    """Run ``fn`` with the multi-objective weight knobs pinned via env
    (cfg reads env live; the kernels treat weights as static, so each
    distinct set compiles once)."""
    saved = {k: os.environ.get(k) for k, _ in _WEIGHT_ENV}
    try:
        for (k, _), v in zip(_WEIGHT_ENV, weights):
            os.environ[k] = repr(float(v))
        return fn()
    finally:
        for k, _ in _WEIGHT_ENV:
            if saved[k] is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = saved[k]


def run_sim_weights_pair(
    num_nodes: int,
    num_demands: int,
    *,
    seed: int = 0,
    weights: Tuple[float, float, float, float] = (1.0, 0.5, 1.0, 1.0),
    large_frac: float = 0.015,
    cpu_scale: float = 1.5,
    hold_rounds: Optional[int] = None,
    starve_rounds: int = 8,
    **kw,
) -> dict:
    """Single-objective (1,0,0,0) vs multi-objective run over the SAME
    seeded heterogeneous topology and skewed CHURN stream (demand
    over-subscribes aggregate capacity; granted work returns its
    capacity after ``hold_rounds`` — the steady-state regime where packing
    quality decides how long large shapes wait): the
    fairness/fragmentation measurement the acceptance criterion pins —
    multi-objective must hold ≥0.8× the single-objective placements/s
    while measurably reducing stranded capacity and large-shape p99
    wait. Both runs report their numbers; the deltas are computed here.

    ``starve_rounds`` is pinned low for the pair (the sim's rounds are
    ms-scale, so production's default would never age a shape into the
    starving regime inside the run). ``hold_rounds`` defaults to holding
    the cluster NEAR-FULL through the run: grants per round are capped
    at sched_max_batch, so a hold shorter than
    capacity_tasks/sched_max_batch rounds lets returns outpace the
    backlog and the contention regime never arrives (observed at 10k
    nodes: a flat 12-round hold left the fleet 94% idle)."""
    if hold_rounds is None:
        from ray_tpu.config import cfg as _cfg

        avg_cpu_node = sum(f * res["CPU"] for f, _, res, _ in NODE_MIX)
        # probability-weighted small-shape mean CPU (build_demand_maps:
        # 0.70*0.25 + 0.15*0.5 + 0.15*1.0 = 0.4)
        avg_demand_cpu = 0.4 * cpu_scale * (1.0 - large_frac) + (
            LARGE_SHAPE["CPU"] * large_frac * 1.2  # tail doubling
        )
        capacity_tasks = num_nodes * avg_cpu_node / max(avg_demand_cpu, 1e-6)
        hold_rounds = max(
            8, int(1.25 * capacity_tasks / max(1, int(_cfg.sched_max_batch)))
        )
    saved_sr = os.environ.get("RAY_TPU_SCHED_STARVE_ROUNDS")
    os.environ["RAY_TPU_SCHED_STARVE_ROUNDS"] = str(int(starve_rounds))
    try:
        common = dict(
            seed=seed,
            heterogeneous=True,
            large_frac=large_frac,
            cpu_scale=cpu_scale,
            hold_rounds=hold_rounds,
            **kw,
        )
        warm_demands = min(num_demands, 6000)

        def _one(w):
            return _with_weights(
                w,
                lambda: (
                    run_sim(
                        num_nodes, warm_demands, pipeline=True, **common
                    ),  # compile warmup at this weight set
                    run_sim(num_nodes, num_demands, pipeline=True, **common),
                )[1],
            )

        single = _one((1.0, 0.0, 0.0, 0.0))
        multi = _one(weights)
    finally:
        if saved_sr is None:
            os.environ.pop("RAY_TPU_SCHED_STARVE_ROUNDS", None)
        else:
            os.environ["RAY_TPU_SCHED_STARVE_ROUNDS"] = saved_sr
    ratio = (
        multi["placements_per_s"] / single["placements_per_s"]
        if single["placements_per_s"]
        else 0.0
    )
    return {
        "single": single,
        "multi": multi,
        "weights": tuple(weights),
        "hold_rounds": hold_rounds,
        "multi_vs_single_throughput": round(ratio, 3),
        "frag_pct_single": single.get("fragmentation_pct", 0.0),
        "frag_pct_multi": multi.get("fragmentation_pct", 0.0),
        "p99_wait_rounds_large_single": single.get(
            "p99_wait_rounds_large", 0.0
        ),
        "p99_wait_rounds_large_multi": multi.get(
            "p99_wait_rounds_large", 0.0
        ),
        "preempt_nominations": multi.get("preempt_nominations", 0),
        "preemptions": multi.get("preemptions", 0),
    }
