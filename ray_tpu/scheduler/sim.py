"""Simulated-scale scheduler harness: 10k nodes, up to 1M pending demands.

Drives the REAL head scheduling path — ``HeadServer`` with its scheduler
thread, fair batch popping, kernel rounds (pipelined or synchronous),
capacity-capped unparking, and the device-resident mirror — against a
synthetic topology with no agents and no RPC: nodes are injected straight
into the cluster view, and ``_send_grants`` is replaced by a local sink
that tallies delivered placements (the network boundary is exactly where
a simulated cluster stops being real, so that is the seam).

This is how the 10k-node × 1M-pending-task scale target (ROADMAP items
1/3) is measured reproducibly on any host: delivered placements/s
end-to-end through ``head._schedule_batch``, plus the round-latency
percentiles over the run's window. ``bench.py``'s ``sim_sched`` tier runs
it in both pipeline modes and publishes the ratio; tests run it small and
assert zero placement divergence between the modes on identical streams.

Health checking is inert by construction: a node that never appears in
``head._last_report`` reads as gap 0 (the agent-report liveness contract
starts at first report), so the synthetic nodes stay alive without a
reporter thread.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.util.metrics import percentile_from_buckets


def build_demand_maps(
    num_demands: int, seed: int = 0
) -> List[Dict[str, float]]:
    """The bench workload's CPU/memory mixture (bench.py build_demands),
    minus the TPU slice — the sim asserts full delivery, so every shape
    must be cluster-placeable."""
    rng = np.random.default_rng(seed)
    kind = rng.choice(3, num_demands, p=[0.70, 0.15, 0.15])
    shapes = (
        {"CPU": 0.25},
        {"CPU": 0.5, "memory": 1.0},
        {"CPU": 1.0},
    )
    return [dict(shapes[k]) for k in kind]


def run_sim(
    num_nodes: int = 10_000,
    num_demands: int = 1_000_000,
    *,
    pipeline: bool = True,
    seed: int = 0,
    cpu_per_node: float = 64.0,
    memory_per_node: float = 256.0,
    collect_assignments: bool = False,
    timeout_s: float = 900.0,
) -> dict:
    """One sim run; returns delivered placements/s + round percentiles.

    ``pipeline`` toggles RAY_TPU_SCHED_PIPELINE for the run (restored
    after), selecting pipelined vs synchronous rounds through the exact
    production code path. All demands are enqueued under the head lock
    BEFORE the scheduler thread can pop, so two runs with the same seed
    see identical batch streams — the basis of the divergence check.
    """
    from ray_tpu.cluster.common import LeaseRequest, NodeInfo
    from ray_tpu.cluster.head import SCHED_ROUND_MS, HeadServer

    env_before = os.environ.get("RAY_TPU_SCHED_PIPELINE")
    os.environ["RAY_TPU_SCHED_PIPELINE"] = "1" if pipeline else "0"
    head = None
    try:
        head = HeadServer(dashboard_port=None)
        delivered = 0
        assignments: Dict[str, str] = {}
        done = threading.Event()
        sink_lock = threading.Lock()

        def grant_sink(grants: Dict[str, List[LeaseRequest]]) -> None:
            nonlocal delivered
            n = sum(len(v) for v in grants.values())
            with sink_lock:
                if collect_assignments:
                    for nid, specs in grants.items():
                        for s in specs:
                            assignments[s.task_id] = nid
                delivered += n
                if delivered >= num_demands:
                    done.set()

        head._send_grants = grant_sink

        with head._cond:
            for i in range(num_nodes):
                nid = f"simnode-{i}"
                head.nodes[nid] = NodeInfo(
                    node_id=nid,
                    address="",
                    resources={
                        "CPU": cpu_per_node,
                        "memory": memory_per_node,
                    },
                )
                head.view.add_node(nid, head.nodes[nid].resources)

        specs = [
            LeaseRequest(
                task_id=f"sim-{i}",
                name="sim",
                payload=b"",
                return_ids=[],
                resources=res,
                max_retries=0,
            )
            for i, res in enumerate(build_demand_maps(num_demands, seed))
        ]

        round_buckets0 = SCHED_ROUND_MS.buckets_snapshot()
        t0 = time.perf_counter()
        with head._cond:
            head._pending.extend(specs)
            head._cond.notify_all()
        completed = done.wait(timeout=timeout_s)
        elapsed = time.perf_counter() - t0
        round_buckets1 = SCHED_ROUND_MS.buckets_snapshot()
        delta = [b1 - b0 for b0, b1 in zip(round_buckets0, round_buckets1)]

        ds = head._lazy_device._result
        out = {
            "pipeline": pipeline,
            "num_nodes": num_nodes,
            "num_demands": num_demands,
            "delivered": delivered,
            "completed": completed,
            "elapsed_s": round(elapsed, 3),
            "placements_per_s": round(delivered / elapsed, 1)
            if elapsed > 0
            else 0.0,
            "sched_round_p50_ms": round(
                percentile_from_buckets(
                    SCHED_ROUND_MS.boundaries, delta, 0.50
                ),
                3,
            ),
            "sched_round_p99_ms": round(
                percentile_from_buckets(
                    SCHED_ROUND_MS.boundaries, delta, 0.99
                ),
                3,
            ),
            "sched_rounds": int(sum(delta)),
            "device_stats": dict(ds.stats) if ds is not None else None,
            "pipeline_stats": (
                head._pipeline.stats() if head._pipeline is not None else None
            ),
            "ring_occupancy": ds.ring_occupancy() if ds is not None else 0,
        }
        if collect_assignments:
            out["assignments"] = assignments
        return out
    finally:
        if head is not None:
            head.shutdown(stop_agents=False)
        if env_before is None:
            os.environ.pop("RAY_TPU_SCHED_PIPELINE", None)
        else:
            os.environ["RAY_TPU_SCHED_PIPELINE"] = env_before


def run_sim_pair(
    num_nodes: int, num_demands: int, *, seed: int = 0, **kw
) -> dict:
    """Pipelined + synchronous runs over the SAME demand stream on the
    same host: the speedup ratio and the divergence count (both modes
    must place every spec, on identical nodes per spec when the stream
    is deterministic). This is the bench tier's workhorse.

    A throwaway warmup run at the same node geometry populates the
    process-wide jit cache first — without it the sync run (which goes
    first) pays every kernel compile and the comparison flatters the
    pipeline."""
    from ray_tpu.config import cfg

    warm_demands = min(num_demands, 3 * int(cfg.sched_max_batch))
    run_sim(num_nodes, warm_demands, pipeline=False, seed=seed, **kw)
    sync = run_sim(
        num_nodes, num_demands, pipeline=False, seed=seed,
        collect_assignments=True, **kw
    )
    piped = run_sim(
        num_nodes, num_demands, pipeline=True, seed=seed,
        collect_assignments=True, **kw
    )
    a_sync = sync.pop("assignments")
    a_piped = piped.pop("assignments")
    divergent = sum(
        1
        for tid, nid in a_sync.items()
        if a_piped.get(tid) != nid
    ) + sum(1 for tid in a_piped if tid not in a_sync)
    speedup = (
        piped["placements_per_s"] / sync["placements_per_s"]
        if sync["placements_per_s"]
        else 0.0
    )
    return {
        "sync": sync,
        "pipelined": piped,
        "placement_divergence": divergent,
        "pipeline_speedup": round(speedup, 2),
    }
