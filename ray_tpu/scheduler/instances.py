"""Per-node accelerator instance assignment (chip index bookkeeping).

Analog of the reference's ResourceInstanceSet + TPU accelerator manager
(/root/reference/src/ray/common/scheduling/resource_instance_set.h,
python/ray/_private/accelerators/tpu.py:38-56): the scheduler's scalar
ledger answers "how many chips are free"; this answers "WHICH chips" so a
granted lease can pin `TPU_VISIBLE_CHIPS` (or `CUDA_VISIBLE_DEVICES`) and
two co-located actors never touch the same silicon.

Semantics (reference parity, resource_instance_set.cc TryAllocate):
- a demand >= 1 must be an integer and takes that many WHOLE free chips;
- a fractional demand (< 1) packs onto a single chip, sharing it with
  other fractional holders (highest-utilization chip that still fits, so
  fractions consolidate instead of fragmenting every chip).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_EPS = 1e-9

# resource name -> env var the worker exports for a granted lease
ACCELERATOR_ENV_VARS = {
    "TPU": "TPU_VISIBLE_CHIPS",
    "GPU": "CUDA_VISIBLE_DEVICES",
}


class AcceleratorInstanceSet:
    """Index-level free list for one accelerator resource on one node."""

    def __init__(self, num_instances: int):
        self.num_instances = int(num_instances)
        # fraction of each chip currently allocated (0.0 = free)
        self._used: List[float] = [0.0] * self.num_instances
        self._lock = threading.Lock()

    def allocate(self, amount: float) -> Optional[List[Tuple[int, float]]]:
        """Returns [(chip_index, fraction)] or None if it doesn't fit."""
        with self._lock:
            if amount >= 1.0 - _EPS:
                n = round(amount)
                if abs(amount - n) > _EPS:
                    return None  # >1 demands must be integers (reference rule)
                free = [i for i, u in enumerate(self._used) if u <= _EPS]
                if len(free) < n:
                    return None
                chosen = free[:n]
                for i in chosen:
                    self._used[i] = 1.0
                return [(i, 1.0) for i in chosen]
            # fractional: pack onto the most-utilized chip that still fits
            best = -1
            for i, u in enumerate(self._used):
                if u + amount <= 1.0 + _EPS and (
                    best < 0 or u > self._used[best]
                ):
                    best = i
            if best < 0:
                return None
            self._used[best] += amount
            return [(best, amount)]

    def release(self, assignment: List[Tuple[int, float]]) -> None:
        with self._lock:
            for i, frac in assignment:
                self._used[i] = max(0.0, self._used[i] - frac)

    def snapshot(self) -> List[float]:
        with self._lock:
            return list(self._used)


class NodeAcceleratorState:
    """All accelerator instance sets for one node + env-var rendering."""

    def __init__(self, resources: Dict[str, float]):
        self.sets: Dict[str, AcceleratorInstanceSet] = {}
        for name in ACCELERATOR_ENV_VARS:
            n = int(resources.get(name, 0))
            if n > 0:
                self.sets[name] = AcceleratorInstanceSet(n)

    def allocate(
        self, demands: Dict[str, float]
    ) -> Optional[Dict[str, List[Tuple[int, float]]]]:
        """Atomically assign chip indices for every accelerator demand in
        the lease; None if any doesn't fit (caller keeps the scalar grant —
        a scalar-feasible integer demand always fits, fragmentation can
        only reject fractional shares)."""
        taken: Dict[str, List[Tuple[int, float]]] = {}
        for name, amount in demands.items():
            s = self.sets.get(name)
            if s is None or amount <= _EPS:
                continue
            got = s.allocate(amount)
            if got is None:
                for n2, a2 in taken.items():
                    self.sets[n2].release(a2)
                return None
            taken[name] = got
        return taken

    def release(self, assignment: Dict[str, List[Tuple[int, float]]]) -> None:
        for name, a in (assignment or {}).items():
            s = self.sets.get(name)
            if s is not None:
                s.release(a)

    @staticmethod
    def env_for(assignment: Dict[str, List[Tuple[int, float]]]) -> Dict[str, str]:
        """Render `TPU_VISIBLE_CHIPS` / `CUDA_VISIBLE_DEVICES` for a lease
        (python/ray/_private/accelerators/tpu.py set_current_process_visible
        analog)."""
        env: Dict[str, str] = {}
        for name, a in (assignment or {}).items():
            var = ACCELERATOR_ENV_VARS.get(name)
            if var and a:
                env[var] = ",".join(str(i) for i, _ in a)
        return env
