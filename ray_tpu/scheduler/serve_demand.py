"""Serve pressure → scheduler demand rows (disaggregated serving, PR 18).

The router fleet's budget reconcile exports per-tenant SERVE pressure —
queued prefill tokens and parked request counts from every admission
shard — and this module converts it into the demand-row form the
existing multi-objective autoscaler kernel (:mod:`.binpack`) consumes.
Capacity then follows serve pressure, not just CPU/TPU counts: a
deployment whose tenants queue prefill tokens faster than its replicas
drain them shows up as unfulfilled demand rows, exactly like a pending
task backlog does, and the resulting ``capacity_hint`` rides the budget
reply back to the fleet where the SLO autoscaler treats it as an
upscale signal.

Synergy-style resource-sensitive shaping (arxiv 2110.06073): demand is
expressed in REPLICA-equivalents — ``tokens_per_replica`` queued prefill
tokens or ``queue_per_replica`` parked requests justify one more
replica-shaped row — with the per-term weighting left to the kernel's
demand sort (complex-first, heavy-first).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


def pressure_rollup(reports: Dict[str, dict]) -> Dict[str, dict]:
    """Merge per-router pressure reports into one per-tenant view.
    ``reports`` maps router_id → report row whose ``pressure`` entry is
    ``{tenant: {"waiting": n, "waiting_tokens": t}}`` (shards see
    disjoint tenants by construction of the hash ring, but a mid-
    reconcile handoff can briefly double-report — summing is the
    conservative choice)."""
    out: Dict[str, dict] = {}
    for rep in reports.values():
        for tenant, row in (rep.get("pressure") or {}).items():
            agg = out.setdefault(
                tenant, {"waiting": 0, "waiting_tokens": 0}
            )
            agg["waiting"] += int(row.get("waiting") or 0)
            agg["waiting_tokens"] += int(row.get("waiting_tokens") or 0)
    return out


def pressure_to_demand_rows(
    pressure: Dict[str, dict],
    *,
    tokens_per_replica: float = 4096.0,
    queue_per_replica: float = 8.0,
    cpu_per_replica: float = 1.0,
    max_rows: int = 64,
    width: int = 1,
    cpu_col: int = 0,
) -> Tuple[np.ndarray, List[str]]:
    """Per-tenant serve pressure → dense demand rows ``f32[B, width]``
    (``cpu_per_replica`` CPU-equivalents in column ``cpu_col``, zeros
    elsewhere) plus the tenant each row belongs to. The default
    ``width=1`` keeps the PR 18 single-axis form for ``capacity_plan``;
    the unified elasticity controller passes the full resource width so
    serve rows solve in the same matrix as gang and task shapes. A
    tenant contributes ``ceil(max(tokens/T, waiting/Q))`` replica-shaped
    rows, capped so one flooding tenant cannot blow up the kernel batch
    (the WFQ weights already bound its actual share)."""
    rows: List[np.ndarray] = []
    owners: List[str] = []
    width = max(1, int(width))
    cpu_col = min(max(0, int(cpu_col)), width - 1)
    shape = np.zeros(width, dtype=np.float32)
    shape[cpu_col] = cpu_per_replica
    for tenant in sorted(pressure):
        row = pressure[tenant]
        tokens = float(row.get("waiting_tokens") or 0)
        waiting = float(row.get("waiting") or 0)
        need = max(
            tokens / max(tokens_per_replica, 1.0),
            waiting / max(queue_per_replica, 1.0),
        )
        n = int(np.ceil(need))
        for _ in range(min(n, max_rows - len(rows))):
            rows.append(shape)
            owners.append(tenant)
        if len(rows) >= max_rows:
            break
    if not rows:
        return np.zeros((0, width), dtype=np.float32), owners
    demands = np.stack(rows).astype(np.float32)
    return demands, owners


def capacity_plan(
    avail_cpu_rows: List[float],
    pressure: Dict[str, dict],
    *,
    tokens_per_replica: float = 4096.0,
    queue_per_replica: float = 8.0,
    cpu_per_replica: float = 1.0,
    max_rows: int = 64,
) -> Optional[dict]:
    """Feed serve demand through the autoscaler's first-fit kernel
    against the cluster's residual CPU rows. Returns the capacity hint
    ``{"replicas_wanted", "replicas_placeable", "unfulfilled",
    "by_tenant"}`` or None when there is no pressure (so callers can
    skip the device work entirely on the idle path)."""
    demands, owners = pressure_to_demand_rows(
        pressure,
        tokens_per_replica=tokens_per_replica,
        queue_per_replica=queue_per_replica,
        cpu_per_replica=cpu_per_replica,
        max_rows=max_rows,
    )
    if demands.shape[0] == 0:
        return None
    avail = np.asarray(
        [[max(0.0, float(c))] for c in avail_cpu_rows], dtype=np.float32
    )
    if avail.shape[0] == 0:
        return {
            "replicas_wanted": int(demands.shape[0]),
            "replicas_placeable": 0,
            "unfulfilled": int(demands.shape[0]),
            "by_tenant": {
                t: owners.count(t) for t in dict.fromkeys(owners)
            },
        }
    from .binpack import bin_pack_residual, sort_demands

    order = sort_demands(demands)
    result = bin_pack_residual(avail, demands[order])
    node = np.asarray(result.node)
    placed = int((node >= 0).sum())
    by_tenant: Dict[str, int] = {}
    for i, slot in zip(order, node):
        if slot >= 0:
            t = owners[int(i)]
            by_tenant[t] = by_tenant.get(t, 0) + 1
    return {
        "replicas_wanted": int(demands.shape[0]),
        "replicas_placeable": placed,
        "unfulfilled": int(demands.shape[0]) - placed,
        "by_tenant": by_tenant,
    }
