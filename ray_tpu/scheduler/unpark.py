"""Capacity-capped unparking of infeasible-queued work.

Shared by the cluster head and the in-process runtime: re-feeding the
ENTIRE parked queue into the pending queue on every capacity-freeing
event is O(parked²) aggregate scheduling work under a deep backlog (5k
parked specs × ~40 unpark events re-scores ~200k placements to grant
5k) — exactly the storm the reference avoids by leaving unschedulable
scheduling classes parked until resources change and retrying them
per-class (cluster_lease_manager.cc:298 TryScheduleInfeasibleLease +
local_lease_manager.h per-class backoff). Per resource shape, the
grantable-slot count is estimated from the live availability arrays and
only that many specs (+slack for estimate error) unpark; the remainder
stays parked for the next change event.

Slot estimation has two backends: the host NumPy scan (one pass per
shape over a fresh copy of the availability arrays — the original), and
``slots_fn`` — a batched estimator over the scheduler device's RESIDENT
arrays (``DeviceSchedulerState.shape_slots``: one kernel dispatch for
ALL shapes, no host copy, no re-upload). The head passes the device
estimator whenever the device scheduler is live.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

UNPARK_SLACK = 32


def select_unparkable_resilient(
    parked: List[Any],
    avail: Optional[np.ndarray],
    alive: Optional[np.ndarray],
    *,
    device_state: Any,
    slots_fn: Optional[Callable[[np.ndarray], np.ndarray]],
    refetch: Callable[[], Tuple[np.ndarray, np.ndarray]],
    **kwargs: Any,
) -> Tuple[List[Any], List[Any]]:
    """``select_unparkable`` with the device-estimator survival contract
    shared by the head and the single-process runtime: a ``slots_fn``
    failure (it dispatches on the scheduler device mid-scan) must not
    kill the caller's scheduler thread — invalidate the device mirror
    (full re-sync next round) and redo the scan host-side on fresh
    copies from ``refetch`` (called under the caller's locking
    discipline). A raise on the pure-NumPy path is a real bug and
    propagates."""
    try:
        return select_unparkable(
            parked, avail, alive, slots_fn=slots_fn, **kwargs
        )
    except Exception:  # noqa: BLE001 - scheduler must survive
        if slots_fn is None:
            raise
        logger.exception("device slot estimation failed; host scan")
        device_state.invalidate()
        a0, al0 = refetch()
        return select_unparkable(parked, a0, al0, slots_fn=None, **kwargs)


def select_unparkable(
    parked: List[Any],
    avail: Optional[np.ndarray],
    alive: Optional[np.ndarray],
    *,
    is_constrained: Callable[[Any], bool],
    resources_of: Callable[[Any], dict],
    request_of: Callable[[Any], Any],
    slack: int = UNPARK_SLACK,
    reserved: Any = None,
    slots_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
    age_of: Optional[Callable[[Any], int]] = None,
) -> Tuple[List[Any], List[Any]]:
    """(take, keep): specs to re-queue now vs. keep parked.

    ``is_constrained``: shape-capacity math doesn't apply (affinity /
    PG / target-node routed) — those unpark ``slack`` at a time.
    ``request_of`` returns a ResourceRequest (``demands`` keyed by dense
    column, ``dense(width)``). ``reserved``: dense demand rows already
    granted but not yet reflected in ``avail`` (e.g. worker leases being
    placed — the agent's ledger deduction reaches the view only with its
    next report); each reserved row that overlaps a shape's demand
    columns is assumed to consume one of that shape's slots.
    ``slots_fn``: batched slot estimator f32[S,R] → int[S] (the
    device-resident path); when given, ``avail``/``alive`` are only used
    for the resource-axis width and may be the live views (no copy
    needed — they are never scanned host-side).
    ``age_of``: optional shape-key → wait-age lookup (head._shape_wait);
    shapes unpark in age-descending order so a STARVING shape claims the
    grantable slots before younger shapes re-consume the freed capacity
    (the unpark half of the starvation/fairness term)."""
    if len(parked) <= slack:
        return list(parked), []
    r = avail.shape[1] if avail is not None and avail.ndim == 2 else 0
    by_shape: dict = {}
    order: List[Any] = []
    for spec in parked:
        if is_constrained(spec):
            key: Any = None
        else:
            key = tuple(sorted(resources_of(spec).items()))
        q = by_shape.get(key)
        if q is None:
            q = by_shape[key] = []
            order.append(key)
        q.append(spec)

    # resolve each unconstrained shape to a dense row (or None: names a
    # resource no node reported — infeasible until the cluster changes
    # shape; slack covers vocab growth)
    dense_rows: dict = {}
    for key in order:
        if key is None:
            continue
        req = request_of(by_shape[key][0])
        if any(c >= r for c in req.demands):
            dense_rows[key] = None
        else:
            dense_rows[key] = req.dense(r)

    slot_counts: dict = {}
    batchable = [k for k in order if k is not None and dense_rows[k] is not None]
    if slots_fn is not None and batchable:
        # one batched kernel over ALL shapes (device-resident arrays)
        mat = np.stack([dense_rows[k] for k in batchable])
        counts = slots_fn(mat)
        for k, c in zip(batchable, counts):
            slot_counts[k] = int(c)
    else:
        for k in batchable:
            d = dense_rows[k]
            cols = d > 0
            if not cols.any():
                slot_counts[k] = len(by_shape[k])  # zero-demand: all grantable
                continue
            slots = np.floor(avail[:, cols] / d[cols][None, :]).min(axis=1)
            slots = np.where(alive, np.maximum(slots, 0.0), 0.0)
            slot_counts[k] = int(slots.sum())

    if age_of is not None:
        # starving shapes first (stable: equal ages keep arrival order)
        order.sort(key=lambda k: -(age_of(k) if k is not None else 0))
    take: List[Any] = []
    keep: List[Any] = []
    for key in order:
        q = by_shape[key]
        if key is None or dense_rows[key] is None:
            cap = slack
        else:
            cap = slot_counts[key]
            d = dense_rows[key]
            cols = d > 0
            if not cols.any():
                cap = len(q)
            elif reserved is not None:
                # outstanding grants eat into the estimate before
                # the view hears about them
                overlap = sum(
                    1
                    for row in reserved
                    if row.shape[0] >= r and (row[:r][cols] > 0).any()
                )
                cap = max(0, cap - overlap)
            if cols.any():
                cap += slack
        n = min(len(q), cap)
        take.extend(q[:n])
        keep.extend(q[n:])
    return take, keep
