"""Capacity-capped unparking of infeasible-queued work.

Shared by the cluster head and the in-process runtime: re-feeding the
ENTIRE parked queue into the pending queue on every capacity-freeing
event is O(parked²) aggregate scheduling work under a deep backlog (5k
parked specs × ~40 unpark events re-scores ~200k placements to grant
5k) — exactly the storm the reference avoids by leaving unschedulable
scheduling classes parked until resources change and retrying them
per-class (cluster_lease_manager.cc:298 TryScheduleInfeasibleLease +
local_lease_manager.h per-class backoff). Per resource shape, the
grantable-slot count is estimated from the live availability arrays and
only that many specs (+slack for estimate error) unpark; the remainder
stays parked for the next change event.
"""
from __future__ import annotations

from typing import Any, Callable, List, Tuple

import numpy as np

UNPARK_SLACK = 32


def select_unparkable(
    parked: List[Any],
    avail: np.ndarray,
    alive: np.ndarray,
    *,
    is_constrained: Callable[[Any], bool],
    resources_of: Callable[[Any], dict],
    request_of: Callable[[Any], Any],
    slack: int = UNPARK_SLACK,
    reserved: Any = None,
) -> Tuple[List[Any], List[Any]]:
    """(take, keep): specs to re-queue now vs. keep parked.

    ``is_constrained``: shape-capacity math doesn't apply (affinity /
    PG / target-node routed) — those unpark ``slack`` at a time.
    ``request_of`` returns a ResourceRequest (``demands`` keyed by dense
    column, ``dense(width)``). ``reserved``: dense demand rows already
    granted but not yet reflected in ``avail`` (e.g. worker leases being
    placed — the agent's ledger deduction reaches the view only with its
    next report); each reserved row that overlaps a shape's demand
    columns is assumed to consume one of that shape's slots."""
    if len(parked) <= slack:
        return list(parked), []
    r = avail.shape[1] if avail.ndim == 2 else 0
    by_shape: dict = {}
    order: List[Any] = []
    for spec in parked:
        if is_constrained(spec):
            key: Any = None
        else:
            key = tuple(sorted(resources_of(spec).items()))
        q = by_shape.get(key)
        if q is None:
            q = by_shape[key] = []
            order.append(key)
        q.append(spec)
    take: List[Any] = []
    keep: List[Any] = []
    for key in order:
        q = by_shape[key]
        if key is None:
            cap = slack
        else:
            req = request_of(q[0])
            if any(c >= r for c in req.demands):
                # names a resource no node reported: infeasible until the
                # cluster changes shape; slack covers vocab growth
                cap = slack
            else:
                d = req.dense(r)
                cols = d > 0
                if not cols.any():
                    cap = len(q)  # zero-demand shape: all grantable
                else:
                    slots = np.floor(
                        avail[:, cols] / d[cols][None, :]
                    ).min(axis=1)
                    slots = np.where(alive, np.maximum(slots, 0.0), 0.0)
                    cap = int(slots.sum())
                    if reserved is not None:
                        # outstanding grants eat into the estimate before
                        # the view hears about them
                        overlap = sum(
                            1
                            for row in reserved
                            if row.shape[0] >= r and (row[:r][cols] > 0).any()
                        )
                        cap = max(0, cap - overlap)
                    cap += slack
        n = min(len(q), cap)
        take.extend(q[:n])
        keep.extend(q[n:])
    return take, keep
