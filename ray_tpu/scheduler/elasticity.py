"""Unified elasticity plane: one on-device demand solve per tick (PR 19).

ROADMAP item 4. Three control loops used to size the same cluster
without seeing each other: the autoscaler packed queued task shapes
(:mod:`.binpack`), the serve SLO autoscaler scaled replicas off router
metrics (serve/slo_autoscaler.py), and every elastic gang's driver
polled free capacity for grow-back (train/elastic.py). A mixed fleet
thrashed — serve upscales raced gang grow-backs for the same nodes and
the autoscaler provisioned blind to both.

This module folds all three demand classes into ONE weighted f32 demand
matrix — grounded in Gavel's heterogeneity-aware scheduling (arxiv
2008.09213: one allocation problem over all jobs, policies as weights)
and Tesserae's scalable placement (arxiv 2508.04953: solve placement as
a single batched program, not per-entity loops) — and runs ONE batched
``solve_pack_counts`` solve on the scheduler device per tick against the
current node rows plus simulated-provisionable rows. The solve's output
drives three coordinated actuations:

- **provision / retire** — hypothetical node columns that received
  demand become real ``cluster_utils.add_node`` calls through the
  attached provider; solver-idle nodes past the idle window are drained
  and retired through the agent lifecycle.
- **serve capacity hints** — per-deployment solver verdicts replace the
  PR 18 one-shot ``capacity_plan`` hint in the budget reply (same dict
  shape, now consistent with what gangs and tasks were granted).
- **drain-ahead migration** — low-priority leased work on a node
  selected for retirement is migrated off via the PR 7 preemption
  machinery (queued → requeue, running retryable → kill-and-requeue
  with no attempt burned) BEFORE the drain deadline, instead of dying
  with the node.

Demand classes and priority. Each class carries a weight knob
(``elastic_w_serve`` / ``elastic_w_gang`` / ``elastic_w_task``); rows
are ordered weight-descending before the solve and the kernel's exact
waterfall extraction admits them in order, so a higher-weighted class
holds first claim on every node's capacity. With the default weights
serve pressure outranks gang grow-back, which outranks queued batch
work — which is exactly the diurnal mixed-fleet story: the gang absorbs
the serve trough (gang rows place once serve rows stop consuming
capacity) and cedes the peak (gang rows lose the waterfall to serve
rows; the per-gang ``world_hint`` shrinks and the driver resizes).

Fallback matrix (COMPONENTS.md "Elasticity plane"):

- solver raises → exact first-fit ``bin_pack_residual`` on the same
  matrix (flagged in the tick stats);
- no provider attached → hint actuations only (external drains still
  migrate through ``Cluster.drain_node``);
- ``RAY_TPU_ELASTIC_CONTROLLER=0`` (default) → this module is inert and
  the three legacy loops run untouched, bit-for-bit.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.config import cfg
from ray_tpu.util.metrics import Counter as _MetricCounter
from ray_tpu.util.metrics import Gauge as _MetricGauge

logger = logging.getLogger(__name__)

# demand classes, by descending default priority
CLASS_SERVE = 0
CLASS_GANG = 1
CLASS_TASK = 2
CLASS_NAMES = {CLASS_SERVE: "serve", CLASS_GANG: "gang", CLASS_TASK: "task"}

ELASTIC_TICKS = _MetricCounter(
    "elastic_controller_ticks_total",
    "Unified elasticity controller ticks, by solve path.",
    label_names=("path",),
)
ELASTIC_TICK_MS = _MetricGauge(
    "elastic_controller_tick_ms",
    "Wall-clock of the last elasticity tick (assemble + solve + plan).",
)
ELASTIC_ACTUATIONS = _MetricCounter(
    "elastic_controller_actuations_total",
    "Elasticity actuations emitted, by kind.",
    label_names=("kind",),
)
ELASTIC_DEMAND_ROWS = _MetricGauge(
    "elastic_demand_rows",
    "Demand rows in the last unified solve, by class.",
    label_names=("cls",),
)


# ---------------------------------------------------------------------------
# satellite 2: parked-demand dedupe
# ---------------------------------------------------------------------------
def dedupe_task_shapes(
    parked: Dict[tuple, int],
    deferred: Dict[tuple, int],
    ring_keys: Sequence[tuple] = (),
) -> Dict[tuple, int]:
    """Merge parked and deferred task demand by shape key.

    A shape that is both ring-parked and sitting in a dispatched-but-
    unread pipelined round (``_deferred_rounds``) is the SAME logical
    backlog seen from two bookkeeping tables — the ring slot pins the
    shape on device while its specs ride the retry pipeline. Summing the
    two sources counted that backlog twice and inflated the solver's
    provision target. For ring-resident shapes the merged demand is
    ``max(parked, deferred)``; shapes the ring does not pin are genuinely
    disjoint queues and still sum.
    """
    ring = set(ring_keys)
    out: Dict[tuple, int] = {}
    for key in set(parked) | set(deferred):
        p = int(parked.get(key, 0))
        d = int(deferred.get(key, 0))
        out[key] = max(p, d) if key in ring else p + d
    return {k: v for k, v in out.items() if v > 0}


# ---------------------------------------------------------------------------
# demand matrix
# ---------------------------------------------------------------------------
@dataclass
class GangWant:
    """One gang's grow-back demand as the head's gang table reports it."""

    gang_id: str
    current: int                 # live members
    want: int                    # target world (driver's max, grow on)
    min_size: int
    row: np.ndarray              # f32[R] resources per rank
    # node_id -> rank count, for crediting current usage back pre-solve
    members_by_node: Dict[str, int] = field(default_factory=dict)

    @property
    def deficit(self) -> int:
        return max(0, int(self.want) - int(self.current))


@dataclass
class ElasticSnapshot:
    """Everything one tick reads, decoupled from the head so the sim
    harness can synthesize 10k-node snapshots without a cluster."""

    width: int                                     # resource axis R
    avail: np.ndarray                              # f32[N,R] residual
    totals: np.ndarray                             # f32[N,R]
    alive: np.ndarray                              # bool[N]
    node_ids: List[str]
    serve_pressure: Dict[str, Dict[str, dict]]     # dep -> tenant -> row
    gang_wants: List[GangWant] = field(default_factory=list)
    task_shapes: Dict[tuple, int] = field(default_factory=dict)
    # node_id -> active lease count (drain-ahead needs to know who still
    # hosts work); absent entries mean idle
    lease_load: Dict[str, int] = field(default_factory=dict)


@dataclass
class DemandMatrix:
    shapes: np.ndarray           # f32[U,R], priority-ordered
    counts: np.ndarray           # f32[U]
    classes: np.ndarray          # int32[U]
    weights: np.ndarray          # f32[U]
    owners: List[tuple]          # per row: ("serve", dep, tenant) |
    #                              ("gang", gang_id) | ("task", shape_key)

    @property
    def rows(self) -> int:
        return int(self.shapes.shape[0])

    def class_counts(self) -> Dict[str, int]:
        out = {name: 0 for name in CLASS_NAMES.values()}
        for c, n in zip(self.classes, self.counts):
            out[CLASS_NAMES[int(c)]] += int(n)
        return out


def class_weights() -> Dict[int, float]:
    return {
        CLASS_SERVE: float(cfg.elastic_w_serve),
        CLASS_GANG: float(cfg.elastic_w_gang),
        CLASS_TASK: float(cfg.elastic_w_task),
    }


def _task_key_row(key: tuple, width: int) -> Optional[np.ndarray]:
    """Dense row for a ``_shape_key_of`` tuple under the head vocabulary
    column order (CPU=0...). Keys name resources by string; the caller
    passes a packer when it has a vocab — this fallback only handles the
    already-dense form used by tests/sim."""
    row = np.zeros(width, dtype=np.float32)
    for name, qty in key:
        if isinstance(name, int):
            col = name
        else:
            return None
        if col >= width:
            return None
        row[col] = float(qty)
    return row


def assemble_demand(
    snap: ElasticSnapshot,
    *,
    weights: Optional[Dict[int, float]] = None,
    pack_key: Optional[Callable[[tuple], Optional[np.ndarray]]] = None,
    max_serve_rows: int = 64,
) -> DemandMatrix:
    """Fold the three demand classes into one priority-ordered matrix.

    Within a class, rows keep the kernel's complex-first/heavy-first
    demand order (``sort_demands``); across classes the configured
    weights order them, so the solve's waterfall extraction hands
    capacity to the highest-weighted class first.
    """
    from ray_tpu.scheduler.serve_demand import pressure_to_demand_rows

    w = weights or class_weights()
    width = snap.width
    shapes: List[np.ndarray] = []
    counts: List[float] = []
    classes: List[int] = []
    owners: List[tuple] = []

    # serve: per-deployment pressure -> replica-shaped rows
    for dep in sorted(snap.serve_pressure):
        rows, tenants = pressure_to_demand_rows(
            snap.serve_pressure[dep],
            max_rows=max_serve_rows,
            width=width,
        )
        # one matrix row per (dep, tenant) shape with a count, not one
        # per replica: the solver consumes (shape, count) pairs
        per_tenant: Dict[str, int] = {}
        for t in tenants:
            per_tenant[t] = per_tenant.get(t, 0) + 1
        for tenant in sorted(per_tenant):
            shapes.append(rows[tenants.index(tenant)])
            counts.append(float(per_tenant[tenant]))
            classes.append(CLASS_SERVE)
            owners.append(("serve", dep, tenant))

    # gang rows carry the FULL want, not the deficit: the solve
    # re-decides every seat each tick (current usage is credited back
    # onto the members' avail rows by credit_gang_usage), so a serve
    # peak outbidding the gang shrinks its verdict BELOW the live world
    # — that is the cede signal the driver fences on
    for gw in snap.gang_wants:
        if gw.want <= 0 or gw.row is None:
            continue
        row = np.zeros(width, dtype=np.float32)
        src = np.asarray(gw.row, dtype=np.float32)
        row[: min(width, src.shape[0])] = src[:width]
        shapes.append(row)
        counts.append(float(gw.want))
        classes.append(CLASS_GANG)
        owners.append(("gang", gw.gang_id))

    # queued/parked/deferred task shapes (already shape-key deduped)
    for key in sorted(snap.task_shapes, key=repr):
        n = snap.task_shapes[key]
        if n <= 0:
            continue
        row = pack_key(key) if pack_key is not None else _task_key_row(key, width)
        if row is None or not (row > 0).any():
            continue
        shapes.append(np.asarray(row[:width], dtype=np.float32))
        counts.append(float(n))
        classes.append(CLASS_TASK)
        owners.append(("task", key))

    if not shapes:
        return DemandMatrix(
            shapes=np.zeros((0, width), dtype=np.float32),
            counts=np.zeros((0,), dtype=np.float32),
            classes=np.zeros((0,), dtype=np.int32),
            weights=np.zeros((0,), dtype=np.float32),
            owners=[],
        )

    mat = np.stack(shapes).astype(np.float32)
    cnt = np.asarray(counts, dtype=np.float32)
    cls = np.asarray(classes, dtype=np.int32)
    wts = np.asarray([w[int(c)] for c in cls], dtype=np.float32)
    # priority order: class weight desc, then complex-first/heavy-first
    # (the binpack demand sort), stable on input order
    complexity = (mat > 0).sum(axis=1)
    heft = mat.sum(axis=1)
    order = np.lexsort(
        (np.arange(len(cnt)), -heft, -complexity, -wts)
    )
    return DemandMatrix(
        shapes=mat[order],
        counts=cnt[order],
        classes=cls[order],
        weights=wts[order],
        owners=[owners[int(i)] for i in order],
    )


def credit_gang_usage(
    avail: np.ndarray,
    node_ids: Sequence[str],
    gang_wants: Sequence[GangWant],
) -> np.ndarray:
    """Copy of ``avail`` with each gang's CURRENT per-rank usage credited
    back onto its members' rows. The demand matrix carries the gang's
    full want (every seat re-decided per tick); without the credit the
    live ranks' own footprint would be double-counted against them and a
    fully-placed gang would read as unplaceable."""
    out = np.asarray(avail, dtype=np.float32).copy()
    if not gang_wants or not out.size:
        return out
    index = {nid: i for i, nid in enumerate(node_ids)}
    for gw in gang_wants:
        if gw.row is None:
            continue
        row = np.asarray(gw.row, dtype=np.float32)[: out.shape[1]]
        for nid, cnt in (gw.members_by_node or {}).items():
            i = index.get(nid)
            if i is not None:
                out[i, : row.shape[0]] += row * float(cnt)
    return out


# ---------------------------------------------------------------------------
# the solve
# ---------------------------------------------------------------------------
@dataclass
class SolvedDemand:
    placed: np.ndarray       # f32[U] — total placed per row (real + hypo)
    per_node: np.ndarray     # f32[U, N+H]
    n_real: int
    n_hypo: int
    path: str                # "solve" | "first_fit"

    def placed_real(self, u: int) -> float:
        return float(self.per_node[u, : self.n_real].sum())

    def placed_hypo(self, u: int) -> float:
        return float(self.per_node[u, self.n_real:].sum())


def solve_demand(
    avail: np.ndarray,
    matrix: DemandMatrix,
    *,
    hypo_rows: Optional[np.ndarray] = None,
    iters: Optional[int] = None,
) -> SolvedDemand:
    """One batched device solve of the unified matrix against the real
    node rows plus ``hypo_rows`` simulated-provisionable rows. The
    shape/node axes are bucket-padded (device.py ``elastic_pack_solve``)
    so tick latency stays one cached XLA program across demand churn.
    Falls back to the exact first-fit kernel when the solve raises."""
    n_real = int(avail.shape[0])
    hypo = (
        np.zeros((0, avail.shape[1]), dtype=np.float32)
        if hypo_rows is None
        else np.asarray(hypo_rows, dtype=np.float32)
    )
    n_hypo = int(hypo.shape[0])
    stacked = np.concatenate([avail.astype(np.float32), hypo], axis=0)
    if matrix.rows == 0 or stacked.shape[0] == 0:
        return SolvedDemand(
            placed=np.zeros((matrix.rows,), dtype=np.float32),
            per_node=np.zeros((matrix.rows, stacked.shape[0]), np.float32),
            n_real=n_real,
            n_hypo=n_hypo,
            path="empty",
        )
    it = int(iters if iters is not None else cfg.autoscaler_solve_iters)
    try:
        from ray_tpu.scheduler.device import elastic_pack_solve

        placed, per_node = elastic_pack_solve(
            stacked, matrix.shapes, matrix.counts, iters=it
        )
        ELASTIC_TICKS.inc(labels={"path": "solve"})
        return SolvedDemand(placed, per_node, n_real, n_hypo, "solve")
    except Exception:  # noqa: BLE001 - fall back to the exact kernel
        logger.exception("elastic solve failed; first-fit fallback")
    from ray_tpu.scheduler.binpack import bin_pack_residual

    # expand (shape, count) -> per-demand rows, first-fit in priority order
    reps = matrix.counts.astype(np.int64)
    demands = np.repeat(matrix.shapes, reps, axis=0)
    import jax.numpy as jnp

    result = bin_pack_residual(
        jnp.asarray(stacked), jnp.asarray(demands)
    )
    node = np.asarray(result.node)
    per_node = np.zeros((matrix.rows, stacked.shape[0]), np.float32)
    placed = np.zeros((matrix.rows,), np.float32)
    starts = np.concatenate([[0], np.cumsum(reps)])
    for u in range(matrix.rows):
        rows = node[starts[u]: starts[u + 1]]
        for r in rows:
            if r >= 0:
                per_node[u, int(r)] += 1.0
                placed[u] += 1.0
    ELASTIC_TICKS.inc(labels={"path": "first_fit"})
    return SolvedDemand(placed, per_node, n_real, n_hypo, "first_fit")


# ---------------------------------------------------------------------------
# actuation plan
# ---------------------------------------------------------------------------
@dataclass
class ElasticPlan:
    provision: int                          # nodes to create this tick
    retire: List[str]                       # node_ids to drain + retire
    migrate: List[str]                      # retiring nodes still hosting work
    serve_hints: Dict[str, dict]            # deployment -> capacity hint
    world_hints: Dict[str, int]             # gang_id -> sustainable world
    unfulfilled: Dict[str, int] = field(default_factory=dict)  # per class
    path: str = "solve"
    tick_ms: float = 0.0
    demand_rows: int = 0

    def summary(self) -> dict:
        return {
            "provision": self.provision,
            "retire": list(self.retire),
            "migrate": list(self.migrate),
            "serve_hints": {
                d: dict(h) for d, h in self.serve_hints.items()
            },
            "world_hints": dict(self.world_hints),
            "unfulfilled": dict(self.unfulfilled),
            "path": self.path,
            "tick_ms": round(self.tick_ms, 3),
            "demand_rows": self.demand_rows,
        }


def build_plan(
    snap: ElasticSnapshot,
    matrix: DemandMatrix,
    solved: SolvedDemand,
    *,
    idle_since: Optional[Dict[str, float]] = None,
    now: Optional[float] = None,
    min_nodes: Optional[int] = None,
    idle_retire_s: Optional[float] = None,
    retire_max: Optional[int] = None,
    provision_max: Optional[int] = None,
) -> ElasticPlan:
    """Map one solve to the three actuations. Pure — unit-testable from a
    fixed solve, no cluster required (satellite 3)."""
    min_nodes = int(min_nodes if min_nodes is not None else cfg.elastic_min_nodes)
    idle_retire_s = float(
        idle_retire_s if idle_retire_s is not None else cfg.elastic_idle_retire_s
    )
    retire_max = int(retire_max if retire_max is not None else cfg.elastic_retire_max)
    provision_max = int(
        provision_max if provision_max is not None else cfg.elastic_provision_max
    )
    now = time.monotonic() if now is None else now

    serve_hints: Dict[str, dict] = {}
    world_hints: Dict[str, int] = {}
    unfulfilled = {name: 0 for name in CLASS_NAMES.values()}
    hypo_used = 0
    for u, owner in enumerate(matrix.owners):
        want = float(matrix.counts[u])
        real = solved.placed_real(u)
        hypo = solved.placed_hypo(u)
        missing = int(round(max(0.0, want - real - hypo)))
        unfulfilled[CLASS_NAMES[int(matrix.classes[u])]] += missing
        if owner[0] == "serve":
            _, dep, tenant = owner
            hint = serve_hints.setdefault(
                dep,
                {
                    "replicas_wanted": 0,
                    "replicas_placeable": 0,
                    "unfulfilled": 0,
                    "by_tenant": {},
                    "source": "elastic_controller",
                },
            )
            hint["replicas_wanted"] += int(round(want))
            hint["replicas_placeable"] += int(round(real))
            hint["unfulfilled"] += int(round(max(0.0, want - real)))
            if real > 0:
                hint["by_tenant"][tenant] = (
                    hint["by_tenant"].get(tenant, 0) + int(round(real))
                )
        elif owner[0] == "gang":
            gid = owner[1]
            world_hints[gid] = world_hints.get(gid, 0) + int(round(real))

    # gang hints ARE the solver's real-fleet verdict, floored at
    # min_size: every seat was re-decided against credited-back avail,
    # so placed < current means a higher class outbid the gang (cede)
    # and placed > current means grow-back capacity exists
    for gw in snap.gang_wants:
        placed = world_hints.get(gw.gang_id)
        if placed is None and gw.want <= 0:
            continue
        world_hints[gw.gang_id] = max(int(gw.min_size), int(placed or 0))

    # provision: hypothetical columns that received any demand
    if solved.n_hypo:
        hypo_cols = solved.per_node[:, solved.n_real:]
        hypo_used = int((hypo_cols.sum(axis=0) > 0).sum())
    provision = min(hypo_used, provision_max)

    # retire: alive nodes the solve left empty AND the view shows idle
    # (nothing running: avail == totals) past the idle window
    retire: List[str] = []
    migrate: List[str] = []
    if retire_max > 0 and snap.avail.shape[0]:
        col_demand = (
            solved.per_node[:, : solved.n_real].sum(axis=0)
            if matrix.rows
            else np.zeros(solved.n_real)
        )
        # best-retire-first ordering (hybrid.retire_scores_impl): fully
        # idle before partially idle, small before big, solver-demanded
        # nodes effectively never
        from ray_tpu.scheduler.hybrid import retire_order

        order = retire_order(snap.totals, snap.avail, col_demand)
        alive_rows = [int(i) for i in order if snap.alive[int(i)]]
        n_alive = len(alive_rows)
        idle_since = idle_since if idle_since is not None else {}
        total_missing = sum(unfulfilled.values())
        total_avail = np.zeros(snap.avail.shape[1], dtype=np.float64)
        for j in alive_rows:
            total_avail += np.maximum(snap.avail[j], 0.0)
        retired_avail = np.zeros_like(total_avail)
        for i in alive_rows:
            if len(retire) >= retire_max or n_alive - len(retire) <= min_nodes:
                break
            if matrix.rows and col_demand[i] > 0:
                continue
            nid = snap.node_ids[i]
            leases = snap.lease_load.get(nid, 0)
            busy = leases > 0 or not np.allclose(
                snap.avail[i], snap.totals[i], atol=1e-3
            )
            if not busy:
                # pure shrink-to-fit: requires the idle window
                since = idle_since.get(nid)
                if since is None or now - since < idle_retire_s:
                    continue
            else:
                # drain-ahead consolidation: a node still hosting leases
                # can retire when every demand row was fully placed
                # without it, the solver landed nothing new on it, and
                # its running work fits elementwise in the rest of the
                # live fleet's residual — migration then moves the
                # leases off before the drain deadline instead of the
                # kill path losing them. Busy-without-leases (actors,
                # serve replicas) has nothing migrate_node_leases can
                # move, so it never consolidation-retires.
                if total_missing > 0 or leases <= 0:
                    continue
                used = np.maximum(snap.totals[i] - snap.avail[i], 0.0)
                rest = (
                    total_avail
                    - retired_avail
                    - np.maximum(snap.avail[i], 0.0)
                )
                if not (rest + 1e-3 >= used).all():
                    continue
            retire.append(nid)
            retired_avail += np.maximum(snap.avail[i], 0.0)
        # drain-ahead: retiring nodes that still host leases get their
        # work migrated before the drain deadline
        migrate = [n for n in retire if snap.lease_load.get(n, 0) > 0]
    return ElasticPlan(
        provision=provision,
        retire=retire,
        migrate=migrate,
        serve_hints=serve_hints,
        world_hints=world_hints,
        unfulfilled=unfulfilled,
        path=solved.path,
    )


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------
class ElasticityController:
    """Head-resident tick loop: snapshot → one device solve → actuate.

    ``head`` is a :class:`~ray_tpu.cluster.head.HeadServer`. ``provider``
    (optional, attachable later) supplies the real agent lifecycle:

    - ``create_node() -> Optional[str]``
    - ``drain_node(node_id, deadline_s) -> bool`` (graceful; falls back
      to ``terminate_node``)
    - ``terminate_node(node_id) -> bool``
    - ``node_template() -> Dict[str, float]`` resources of one
      provisionable node (shapes the hypothetical solve rows)
    """

    def __init__(self, head, provider=None):
        self.head = head
        self.provider = provider
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # RLock: state() reads the plan and the tick percentiles under one
        # acquisition
        self._lock = threading.RLock()
        self._idle_since: Dict[str, float] = {}
        self._tick_ms: List[float] = []
        self.ticks = 0
        self.last_plan: Optional[ElasticPlan] = None
        self._draining: Dict[str, float] = {}  # node_id -> deadline

    # -- lifecycle ------------------------------------------------------
    def attach_provider(self, provider) -> None:
        with self._lock:
            self.provider = provider

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(max(0.05, float(cfg.elastic_tick_s))):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 - controller must not die
                    logger.exception("elasticity tick failed")

        self._thread = threading.Thread(
            target=loop, name="head-elasticity", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- snapshot -------------------------------------------------------
    def snapshot(self) -> ElasticSnapshot:
        """Assemble the unified demand view from the head's tables. Holds
        the head lock only for the cheap copies."""
        head = self.head
        with head._lock:
            totals0, avail0, alive0 = head.view.active_arrays()
            totals = totals0.copy()
            avail = avail0.copy()
            alive = alive0.copy()
            node_ids = [head.view.node_id(i) for i in range(len(alive))]
            serve_pressure = {
                dep: {r: dict(rep) for r, rep in reports.items()}
                for dep, reports in head._serve_budget.items()
            }
        width = int(totals.shape[1]) if totals.size else max(
            1, head.view.totals.shape[1]
        )
        with head._cond:
            gang_wants = []
            for gid, g in head._gangs.items():
                want = int(g.get("want_world") or 0)
                if want <= 0 or not g.get("grow", False):
                    continue
                res = g.get("resources_per_rank") or {"CPU": 1.0}
                row = head.vocab.pack(res).astype(np.float32)[:width]
                if row.shape[0] < width:
                    row = np.pad(row, (0, width - row.shape[0]))
                by_node: Dict[str, int] = {}
                for nid in g["members"].values():
                    by_node[nid] = by_node.get(nid, 0) + 1
                gang_wants.append(
                    GangWant(
                        gang_id=gid,
                        current=len(g["members"]),
                        want=want,
                        min_size=int(g.get("min_size", 1)),
                        row=row,
                        members_by_node=by_node,
                    )
                )
            parked: Dict[tuple, int] = {}
            from ray_tpu.cluster.head import _shape_key_of

            seen: set = set()
            for q in (
                head._pending,
                head._infeasible,
                head._scheduling_batch,
            ):
                for s in q:
                    if not s.resources or id(s) in seen:
                        continue
                    seen.add(id(s))
                    k = _shape_key_of(s)
                    parked[k] = parked.get(k, 0) + 1
            deferred: Dict[tuple, int] = {}
            for specs in head._deferred_rounds.values():
                for s in specs:
                    if not s.resources or id(s) in seen:
                        continue
                    seen.add(id(s))
                    k = _shape_key_of(s)
                    deferred[k] = deferred.get(k, 0) + 1
            device_state = head._lazy_device._result
            ring_keys = (
                list(device_state.ring_keys())
                if device_state is not None
                else []
            )
            lease_load: Dict[str, int] = {}
            for e in head._task_leases.values():
                if e.get("state") == "active" and e.get("node_id"):
                    nid = e["node_id"]
                    lease_load[nid] = lease_load.get(nid, 0) + 1
            for _, (spec, nid) in head._in_flight.items():
                if nid:
                    lease_load[nid] = lease_load.get(nid, 0) + 1
        task_shapes = dedupe_task_shapes(parked, deferred, ring_keys)
        return ElasticSnapshot(
            width=width,
            avail=avail,
            totals=totals,
            alive=alive,
            node_ids=node_ids,
            serve_pressure={
                dep: self._rollup(reports)
                for dep, reports in serve_pressure.items()
            },
            gang_wants=gang_wants,
            task_shapes=task_shapes,
            lease_load=lease_load,
        )

    @staticmethod
    def _rollup(reports: Dict[str, dict]) -> Dict[str, dict]:
        from ray_tpu.scheduler.serve_demand import pressure_rollup

        return pressure_rollup(reports)

    def _pack_key(self, key: tuple) -> Optional[np.ndarray]:
        width = self.head.view.totals.shape[1]
        try:
            row = self.head.vocab.pack(dict(key)).astype(np.float32)
        except Exception:  # noqa: BLE001 - unknown resource name
            return None
        if row.shape[0] < width:
            row = np.pad(row, (0, width - row.shape[0]))
        return row[:width]

    def _hypo_rows(self, width: int) -> np.ndarray:
        k = max(0, int(cfg.elastic_provision_max))
        if k == 0:
            return np.zeros((0, width), dtype=np.float32)
        template: Dict[str, float]
        if self.provider is not None and hasattr(self.provider, "node_template"):
            template = dict(self.provider.node_template() or {})
        else:
            template = {"CPU": float(cfg.elastic_node_cpus)}
        row = self.head.vocab.pack(template).astype(np.float32)
        if row.shape[0] < width:
            row = np.pad(row, (0, width - row.shape[0]))
        return np.tile(row[:width], (k, 1))

    # -- one tick -------------------------------------------------------
    def tick(self) -> dict:
        t0 = time.perf_counter()
        snap = self.snapshot()
        live = snap.alive.astype(bool)
        avail = np.where(live[:, None], snap.avail, 0.0).astype(np.float32)
        avail = credit_gang_usage(avail, snap.node_ids, snap.gang_wants)
        # track idle windows for retirement (busy nodes reset the clock)
        now = time.monotonic()
        for i, nid in enumerate(snap.node_ids):
            idle = (
                bool(live[i])
                and snap.lease_load.get(nid, 0) == 0
                and np.allclose(snap.avail[i], snap.totals[i], atol=1e-3)
            )
            if idle:
                self._idle_since.setdefault(nid, now)
            else:
                self._idle_since.pop(nid, None)
        matrix = assemble_demand(snap, pack_key=self._pack_key)
        for name, n in matrix.class_counts().items():
            ELASTIC_DEMAND_ROWS.set(n, labels={"cls": name})
        solved = solve_demand(
            avail, matrix, hypo_rows=self._hypo_rows(snap.width)
        )
        plan = build_plan(
            snap,
            matrix,
            solved,
            idle_since=self._idle_since,
            now=now,
        )
        plan.tick_ms = (time.perf_counter() - t0) * 1000.0
        plan.demand_rows = matrix.rows
        ELASTIC_TICK_MS.set(plan.tick_ms)
        with self._lock:
            self.ticks += 1
            self.last_plan = plan
            self._tick_ms.append(plan.tick_ms)
            if len(self._tick_ms) > 512:
                del self._tick_ms[:-512]
        self.actuate(plan, snap)
        return plan.summary()

    # -- actuation ------------------------------------------------------
    def actuate(self, plan: ElasticPlan, snap: ElasticSnapshot) -> None:
        head = self.head
        # (b) solver-backed serve capacity hints: land them where the
        # budget reply reads (PR 18 seam), replacing the one-shot plan
        if plan.serve_hints:
            with head._lock:
                for dep, hint in plan.serve_hints.items():
                    head._serve_capacity_hints[dep] = {
                        "hint": dict(hint),
                        "ts": time.monotonic(),
                    }
            ELASTIC_ACTUATIONS.inc(labels={"kind": "serve_hint"})
        # gang world hints ride the gang table; drivers poll via GangHint
        if plan.world_hints:
            with head._cond:
                for gid, world in plan.world_hints.items():
                    g = head._gangs.get(gid)
                    if g is not None:
                        g["world_hint"] = int(world)
                head._cond.notify_all()
            ELASTIC_ACTUATIONS.inc(labels={"kind": "gang_hint"})
        # (a) provision through the real agent lifecycle
        provider = self.provider
        if plan.provision > 0 and provider is not None:
            for _ in range(plan.provision):
                try:
                    nid = provider.create_node()
                except Exception:  # noqa: BLE001
                    logger.exception("elastic provision failed")
                    break
                if nid:
                    ELASTIC_ACTUATIONS.inc(labels={"kind": "provision"})
        # (c) retire with drain-ahead migration. Without a provider there
        # is no terminate path, so beginning a drain would just churn
        # begin/finish every tick — fallback matrix: hint actuations only
        # (external drains still migrate via Cluster.drain_node).
        if provider is None:
            return
        for nid in plan.retire:
            deadline = time.monotonic() + float(cfg.elastic_drain_deadline_s)
            first = nid not in self._draining
            self._draining.setdefault(nid, deadline)
            if first:
                try:
                    head.begin_node_drain(nid)
                except Exception:  # noqa: BLE001
                    logger.exception("begin drain failed for %s", nid)
                if nid in plan.migrate:
                    try:
                        head.migrate_node_leases(nid)
                        ELASTIC_ACTUATIONS.inc(labels={"kind": "migrate"})
                    except Exception:  # noqa: BLE001
                        logger.exception("drain-ahead migrate failed")
        # complete drains whose node emptied (or deadline passed)
        for nid in list(self._draining):
            if nid not in plan.retire and snap.lease_load.get(nid, 0):
                # demand returned before the kill: cancel the drain
                self._draining.pop(nid, None)
                try:
                    head.finish_node_drain(nid, retire=False)
                except Exception:  # noqa: BLE001
                    pass
                continue
            drained = snap.lease_load.get(nid, 0) == 0
            expired = time.monotonic() >= self._draining[nid]
            if not (drained or expired):
                continue
            self._draining.pop(nid, None)
            ok = False
            if provider is not None:
                try:
                    ok = bool(provider.terminate_node(nid))
                except Exception:  # noqa: BLE001
                    logger.exception("elastic retire failed for %s", nid)
            try:
                head.finish_node_drain(nid, retire=ok)
            except Exception:  # noqa: BLE001
                pass
            if ok:
                ELASTIC_ACTUATIONS.inc(labels={"kind": "retire"})

    # -- observability --------------------------------------------------
    def tick_percentiles(self) -> Dict[str, float]:
        with self._lock:
            ms = sorted(self._tick_ms)
        if not ms:
            return {"p50_ms": 0.0, "p99_ms": 0.0}
        return {
            "p50_ms": ms[len(ms) // 2],
            "p99_ms": ms[min(len(ms) - 1, int(len(ms) * 0.99))],
        }

    def state(self) -> dict:
        with self._lock:
            plan = self.last_plan.summary() if self.last_plan else None
            return {
                "ticks": self.ticks,
                "tick": self.tick_percentiles(),
                "draining": {
                    n: round(d - time.monotonic(), 2)
                    for n, d in self._draining.items()
                },
                "last_plan": plan,
                "provider": type(self.provider).__name__
                if self.provider is not None
                else None,
            }
