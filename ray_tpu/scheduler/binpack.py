"""Autoscaler bin-packing kernels.

TPU-batched re-design of the reference autoscaler's demand math
(/root/reference/python/ray/autoscaler/_private/resource_demand_scheduler.py):

- ``bin_pack_residual`` — first-fit packing of pending demands onto node
  resource rows (get_bin_pack_residual, :879-938). The reference walks python
  dicts per demand; here it is one ``lax.scan`` over a dense demand matrix.
- ``utilization_scores`` — the node-type scorer used by get_nodes_for
  (:809-864): simulates filling one node of each type with the demand list
  and returns the 4-component lexicographic key (gpu_ok,
  num_matching_resource_types, min(v·u³), mean(v·u³)) — vmapped over *all*
  node types at once.

Demands must be pre-sorted complex→heavy (``sort_demands``), matching the
reference's `sorted(..., key=(len, sum, items), reverse=True)`.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .resources import GPU, TPU

_EPS = 1e-5


def sort_demands(demands: np.ndarray) -> np.ndarray:
    """Indices ordering demands complex-first, then heavy-first (host-side)."""
    complexity = (demands > 0).sum(axis=1)
    weight = demands.sum(axis=1)
    # reverse=True on (len, sum); stable original order as final tie-break.
    return np.lexsort((np.arange(len(demands)), -weight, -complexity))


class BinPackResult(NamedTuple):
    node: jax.Array       # int32[B] node row per demand, -1 = unfulfilled
    avail_out: jax.Array  # f32[N,R] residual node resources


@functools.partial(jax.jit, static_argnames=("strict_spread",))
def bin_pack_residual(
    nodes_avail: jax.Array,  # f32[N,R]
    demands: jax.Array,      # f32[B,R], pre-sorted complex→heavy
    *,
    strict_spread: bool = False,
) -> BinPackResult:
    """First-fit packing; the kernel behind autoscaler demand satisfaction."""
    n = nodes_avail.shape[0]

    def step(state, d):
        avail, used = state
        fits = jnp.all(avail >= d[None, :] - _EPS, axis=1) & ~used
        any_fit = jnp.any(fits)
        chosen = jnp.argmax(fits)  # first fitting node (reference iterates in order)
        avail = jnp.where(any_fit, avail.at[chosen].add(-d), avail)
        if strict_spread:
            used = used.at[chosen].set(jnp.where(any_fit, True, used[chosen]))
        node = jnp.where(any_fit, chosen.astype(jnp.int32), -1)
        return (avail, used), node

    (avail_out, _), nodes = jax.lax.scan(
        step, (nodes_avail, jnp.zeros((n,), dtype=bool)), demands
    )
    return BinPackResult(nodes, avail_out)


class TypeScore(NamedTuple):
    feasible: jax.Array   # bool[T] — at least one demand fits this type
    gpu_ok: jax.Array     # bool[T]
    num_matching: jax.Array  # int32[T]
    min_util: jax.Array   # f32[T]
    mean_util: jax.Array  # f32[T]


@functools.partial(jax.jit, static_argnames=("conserve_accel_nodes",))
def utilization_scores(
    node_types: jax.Array,  # f32[T,R] resources of one node of each type
    demands: jax.Array,     # f32[B,R] pre-sorted
    *,
    conserve_accel_nodes: bool = True,
) -> TypeScore:
    """_resource_based_utilization_scorer semantics, vmapped over types."""
    resource_types_mask = jnp.any(demands > 0, axis=0)  # bool[R]
    any_accel_task = jnp.any(demands[:, (GPU, TPU),] > 0)

    def score_one(node: jax.Array):
        def fill(remaining, d):
            fits = jnp.all(remaining >= d - _EPS)
            remaining = jnp.where(fits, remaining - d, remaining)
            return remaining, fits

        remaining, fit_flags = jax.lax.scan(fill, node, demands)
        feasible = jnp.any(fit_flags)
        valid = node >= 1.0  # reference skips v < 1 (resources are ~integers)
        util = jnp.where(valid, (node - remaining) / jnp.where(valid, node, 1.0), 0.0)
        ubr = node * util**3  # v · u³ per resource
        big = jnp.float32(jnp.inf)
        min_util = jnp.min(jnp.where(valid, ubr, big))
        cnt = jnp.sum(valid.astype(jnp.float32))
        mean_util = jnp.sum(jnp.where(valid, ubr, 0.0)) / jnp.maximum(cnt, 1.0)
        num_matching = jnp.sum((valid & resource_types_mask).astype(jnp.int32))
        is_accel_node = jnp.any(node[(GPU, TPU),] > 0)
        if conserve_accel_nodes:
            gpu_ok = ~(is_accel_node & ~any_accel_task)
        else:
            gpu_ok = jnp.bool_(True)
        feasible = feasible & (cnt > 0)
        return feasible, gpu_ok, num_matching, min_util, mean_util

    f, g, m, mn, me = jax.vmap(score_one)(node_types)
    return TypeScore(f, g, m, mn, me)


class DeltaBinPacker:
    """Device-resident node rows for the autoscaler's residual packing.

    The autoscaler re-packed its availability matrix from python dicts and
    re-uploaded it every tick. This keeps the node rows resident on the
    scheduler device under the same host-mirror/dirty-row protocol as
    DeviceSchedulerState (scheduler/device.py): per tick, rows whose host
    value changed are scatter-pushed; membership or geometry changes
    trigger a full re-upload. Node and demand axes are bucket-padded
    (zero rows — a real demand never fits one, and first-fit prefers the
    earlier real rows for zero demands) so steady ticks hit the jit cache.
    """

    def __init__(self):
        self._ids: Tuple = ()
        self._mirror = None   # f32[C,R] host
        self._dev = None      # f32[C,R] device
        self._push = None

    @staticmethod
    def _bucket(n: int, floor: int = 8) -> int:
        from .device import _bucket

        return _bucket(n, floor)

    def pack(self, node_ids, rows, demands: np.ndarray) -> np.ndarray:
        """First-fit ``demands`` onto the keyed node ``rows``; returns
        int32[B] row index per demand (-1 = unfulfilled). ``node_ids``
        key the delta detection — reordered/renamed ids full-sync."""
        import jax

        rows = np.asarray(rows, dtype=np.float32)
        n, r = rows.shape
        ids = tuple(node_ids)
        n_pad = self._bucket(n)
        if self._push is None:
            self._push = jax.jit(
                lambda a, rws, vals: a.at[rws].set(vals), donate_argnums=(0,)
            )
        if (
            self._mirror is None
            or ids != self._ids
            or self._mirror.shape != (n_pad, r)
        ):
            self._mirror = np.zeros((n_pad, r), dtype=np.float32)
            self._mirror[:n] = rows
            self._dev = jax.device_put(self._mirror)
            self._ids = ids
        else:
            dirty = np.flatnonzero(np.any(self._mirror[:n] != rows, axis=1))
            if dirty.size:
                from .device import pad_scatter

                self._mirror[dirty] = rows[dirty]
                drows, dvals = pad_scatter(
                    dirty.astype(np.int32), self._mirror[dirty]
                )
                self._dev = self._push(self._dev, drows, dvals)
        b = demands.shape[0]
        b_pad = self._bucket(b, 1)
        dmat = np.zeros((b_pad, r), dtype=np.float32)
        dmat[:b] = demands
        res = bin_pack_residual(self._dev, dmat)
        nodes = np.asarray(res.node)[:b].copy()
        nodes[nodes >= n] = -1  # a pad row can never really host a demand
        return nodes


def pick_best_node_type(scores: TypeScore) -> int:
    """Lexicographic argmax over (gpu_ok, num_matching, min_util, mean_util);
    -1 if no type is feasible. Host-side: T is small."""
    f = np.asarray(scores.feasible)
    if not f.any():
        return -1
    key = np.stack(
        [
            np.asarray(scores.gpu_ok, dtype=np.float64),
            np.asarray(scores.num_matching, dtype=np.float64),
            np.asarray(scores.min_util, dtype=np.float64),
            np.asarray(scores.mean_util, dtype=np.float64),
        ],
        axis=1,
    )
    key[~f] = -np.inf
    # np.lexsort sorts ascending by last key primary; we want max.
    order = np.lexsort((key[:, 3], key[:, 2], key[:, 1], key[:, 0]))
    return int(order[-1])
