"""Autoscaler bin-packing kernels.

TPU-batched re-design of the reference autoscaler's demand math
(/root/reference/python/ray/autoscaler/_private/resource_demand_scheduler.py):

- ``bin_pack_residual`` — first-fit packing of pending demands onto node
  resource rows (get_bin_pack_residual, :879-938). The reference walks python
  dicts per demand; here it is one ``lax.scan`` over a dense demand matrix.
- ``utilization_scores`` — the node-type scorer used by get_nodes_for
  (:809-864): simulates filling one node of each type with the demand list
  and returns the 4-component lexicographic key (gpu_ok,
  num_matching_resource_types, min(v·u³), mean(v·u³)) — vmapped over *all*
  node types at once.

Demands must be pre-sorted complex→heavy (``sort_demands``), matching the
reference's `sorted(..., key=(len, sum, items), reverse=True)`.

ISSUE 7: the first-fit ``lax.scan`` is O(B) sequential steps — at a
six-figure pending backlog the autoscaler tick was the last host-side
O(demands) pass in the scheduling plane. ``solve_pack_counts`` replaces
it on the big-batch path with a CvxCluster-style (arxiv 2605.01614)
batched iterative solve over the DEDUPED (shape, count) form: a fixed
number of projected-gradient fill/repair iterations shapes a fractional
allocation x[U,N] jointly across all shapes, and one exact waterfall
extraction pass (same cumulative-capacity math as the ring kernel)
converts it to integral placements that never over-commit a node. U is
orders of magnitude smaller than B, so the scan shrinks 100-1000×; the
first-fit kernel stays as the small-batch path, the failure fallback,
and the differential-test oracle (``DeltaBinPacker.pack``).
"""
from __future__ import annotations

import functools
import logging
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.util.metrics import Counter as _MetricCounter
from ray_tpu.util.metrics import Gauge as _MetricGauge

from .resources import GPU, TPU

logger = logging.getLogger(__name__)

_EPS = 1e-5

# autoscaler solve health (surfaced via head QueryState("sched") so a
# solver regression is observable without a bench run)
SOLVER_RUNS = _MetricCounter(
    "autoscaler_solver_runs_total",
    "Autoscaler residual bin-packs solved by the projected-gradient "
    "kernel (vs the first-fit scan).",
)
SOLVER_FALLBACKS = _MetricCounter(
    "autoscaler_solver_fallbacks_total",
    "Projected-gradient solves that failed and fell back to the exact "
    "first-fit kernel.",
)
SOLVER_ITERS = _MetricGauge(
    "autoscaler_solver_iters",
    "Fixed projected-gradient iteration count of the last solve.",
)


def sort_demands(demands: np.ndarray) -> np.ndarray:
    """Indices ordering demands complex-first, then heavy-first (host-side)."""
    complexity = (demands > 0).sum(axis=1)
    weight = demands.sum(axis=1)
    # reverse=True on (len, sum); stable original order as final tie-break.
    return np.lexsort((np.arange(len(demands)), -weight, -complexity))


class BinPackResult(NamedTuple):
    node: jax.Array       # int32[B] node row per demand, -1 = unfulfilled
    avail_out: jax.Array  # f32[N,R] residual node resources


@functools.partial(jax.jit, static_argnames=("strict_spread",))
def bin_pack_residual(
    nodes_avail: jax.Array,  # f32[N,R]
    demands: jax.Array,      # f32[B,R], pre-sorted complex→heavy
    *,
    strict_spread: bool = False,
) -> BinPackResult:
    """First-fit packing; the kernel behind autoscaler demand satisfaction."""
    n = nodes_avail.shape[0]

    def step(state, d):
        avail, used = state
        fits = jnp.all(avail >= d[None, :] - _EPS, axis=1) & ~used
        any_fit = jnp.any(fits)
        chosen = jnp.argmax(fits)  # first fitting node (reference iterates in order)
        avail = jnp.where(any_fit, avail.at[chosen].add(-d), avail)
        if strict_spread:
            used = used.at[chosen].set(jnp.where(any_fit, True, used[chosen]))
        node = jnp.where(any_fit, chosen.astype(jnp.int32), -1)
        return (avail, used), node

    (avail_out, _), nodes = jax.lax.scan(
        step, (nodes_avail, jnp.zeros((n,), dtype=bool)), demands
    )
    return BinPackResult(nodes, avail_out)


class SolveResult(NamedTuple):
    placed: jax.Array    # f32[U] requests placed per demand shape
    per_node: jax.Array  # f32[U,N] integral placements per node per shape
    avail_out: jax.Array  # f32[N,R] residual node resources


@functools.partial(jax.jit, static_argnames=("iters",))
def solve_pack_counts(
    nodes_avail: jax.Array,  # f32[N,R]
    shapes: jax.Array,       # f32[U,R] unique demand shapes
    counts: jax.Array,       # f32[U] pending requests per shape
    *,
    iters: int = 24,
) -> SolveResult:
    """Projected-gradient residual packing over (shape, count) pairs.

    Phase 1 — fixed-iteration fill/repair on a fractional allocation
    x[U,N] (CvxCluster's batched iterative solve shape): each iteration
    (a) pushes every under-served shape's remaining count onto nodes
    proportionally to their remaining headroom for that shape, then (b)
    steps down the gradient of the squared per-node capacity violation
    ``0.5·||relu(x·d − avail)||²`` — all shapes jointly, a handful of
    fused [U,N]/[N,R] ops per iteration, no per-demand scan.

    Phase 2 — exact extraction: one waterfall scan over the U shapes
    (the ring kernel's cumulative-capacity fill) that admits integral
    placements following each shape's solver-preferred node order, so
    the result can never over-commit a node regardless of how converged
    phase 1 is, and always places as much of each shape as sequential
    greedy could.
    """
    n, r = nodes_avail.shape
    u = shapes.shape[0]
    demanded = shapes > 0  # [U,R]

    def cap_of(args):
        d, dm = args
        ratio = jnp.where(
            dm[None, :],
            jnp.floor((nodes_avail + _EPS) / jnp.where(dm, d, 1.0)[None, :]),
            jnp.inf,
        )
        cap = jnp.min(ratio, axis=1)
        return jnp.where(jnp.any(dm), jnp.maximum(cap, 0.0), 0.0)

    cap0 = jax.lax.map(cap_of, (shapes, demanded))  # f32[U,N]

    def fill_repair(x, _):
        # (a) fill: distribute unserved count over remaining headroom
        head = jnp.maximum(cap0 - x, 0.0)
        head_sum = jnp.maximum(jnp.sum(head, axis=1, keepdims=True), _EPS)
        slack = jnp.maximum(counts - jnp.sum(x, axis=1), 0.0)
        x = x + slack[:, None] * head / head_sum
        # (b) repair: gradient step down the capacity violation
        load = jnp.einsum("un,ur->nr", x, shapes)
        over = jnp.maximum(load - nodes_avail, 0.0)
        grad = jnp.einsum("ur,nr->un", shapes, over)  # d violation / d x
        scale = jnp.maximum(jnp.sum(shapes * shapes, axis=1), _EPS)
        x = jnp.maximum(x - grad / scale[:, None], 0.0)
        # (c) project: never allocate more than the shape's count
        tot = jnp.maximum(jnp.sum(x, axis=1), _EPS)
        x = x * jnp.minimum(counts / tot, 1.0)[:, None]
        return x, None

    x0 = jnp.zeros((u, n), dtype=jnp.float32)
    x, _ = jax.lax.scan(fill_repair, x0, None, length=iters)

    def per_shape(avail_run, uidx):
        d = shapes[uidx]
        dm = demanded[uidx]
        want = counts[uidx]
        ratio = jnp.where(
            dm[None, :],
            jnp.floor((avail_run + _EPS) / jnp.where(dm, d, 1.0)[None, :]),
            jnp.inf,
        )
        cap = jnp.min(ratio, axis=1)
        has_demand = jnp.any(dm)
        cap = jnp.where(has_demand, jnp.maximum(cap, 0.0), want)
        # solver-preferred node order (node index breaks ties, mirroring
        # first-fit's in-order walk)
        order = jnp.argsort(-x[uidx], stable=True)
        cap_sorted = cap[order]
        cap_fin = jnp.where(jnp.isfinite(cap_sorted), cap_sorted, want)
        cum_prev = jnp.concatenate(
            [jnp.zeros((1,), cap_fin.dtype), jnp.cumsum(cap_fin)[:-1]]
        )
        take_sorted = jnp.clip(want - cum_prev, 0.0, cap_fin)
        per_node = jnp.zeros((n,), jnp.float32).at[order].set(take_sorted)
        avail_run = jnp.where(
            has_demand, avail_run - per_node[:, None] * d[None, :], avail_run
        )
        return avail_run, (jnp.sum(take_sorted), per_node)

    avail_out, (placed, per_node) = jax.lax.scan(
        per_shape, nodes_avail, jnp.arange(u, dtype=jnp.int32)
    )
    return SolveResult(placed, per_node, avail_out)


class TypeScore(NamedTuple):
    feasible: jax.Array   # bool[T] — at least one demand fits this type
    gpu_ok: jax.Array     # bool[T]
    num_matching: jax.Array  # int32[T]
    min_util: jax.Array   # f32[T]
    mean_util: jax.Array  # f32[T]


@functools.partial(jax.jit, static_argnames=("conserve_accel_nodes",))
def utilization_scores(
    node_types: jax.Array,  # f32[T,R] resources of one node of each type
    demands: jax.Array,     # f32[B,R] pre-sorted
    *,
    conserve_accel_nodes: bool = True,
) -> TypeScore:
    """_resource_based_utilization_scorer semantics, vmapped over types."""
    resource_types_mask = jnp.any(demands > 0, axis=0)  # bool[R]
    any_accel_task = jnp.any(demands[:, (GPU, TPU),] > 0)

    def score_one(node: jax.Array):
        def fill(remaining, d):
            fits = jnp.all(remaining >= d - _EPS)
            remaining = jnp.where(fits, remaining - d, remaining)
            return remaining, fits

        remaining, fit_flags = jax.lax.scan(fill, node, demands)
        feasible = jnp.any(fit_flags)
        valid = node >= 1.0  # reference skips v < 1 (resources are ~integers)
        util = jnp.where(valid, (node - remaining) / jnp.where(valid, node, 1.0), 0.0)
        ubr = node * util**3  # v · u³ per resource
        big = jnp.float32(jnp.inf)
        min_util = jnp.min(jnp.where(valid, ubr, big))
        cnt = jnp.sum(valid.astype(jnp.float32))
        mean_util = jnp.sum(jnp.where(valid, ubr, 0.0)) / jnp.maximum(cnt, 1.0)
        num_matching = jnp.sum((valid & resource_types_mask).astype(jnp.int32))
        is_accel_node = jnp.any(node[(GPU, TPU),] > 0)
        if conserve_accel_nodes:
            gpu_ok = ~(is_accel_node & ~any_accel_task)
        else:
            gpu_ok = jnp.bool_(True)
        feasible = feasible & (cnt > 0)
        return feasible, gpu_ok, num_matching, min_util, mean_util

    f, g, m, mn, me = jax.vmap(score_one)(node_types)
    return TypeScore(f, g, m, mn, me)


class DeltaBinPacker:
    """Device-resident node rows for the autoscaler's residual packing.

    The autoscaler re-packed its availability matrix from python dicts and
    re-uploaded it every tick. This keeps the node rows resident on the
    scheduler device under the same host-mirror/dirty-row protocol as
    DeviceSchedulerState (scheduler/device.py): per tick, rows whose host
    value changed are scatter-pushed; membership or geometry changes
    trigger a full re-upload. Node and demand axes are bucket-padded
    (zero rows — a real demand never fits one, and first-fit prefers the
    earlier real rows for zero demands) so steady ticks hit the jit cache.
    """

    def __init__(self):
        self._ids: Tuple = ()
        self._mirror = None   # f32[C,R] host
        self._dev = None      # f32[C,R] device
        self._push = None

    @staticmethod
    def _bucket(n: int, floor: int = 8) -> int:
        from .device import _bucket

        return _bucket(n, floor)

    def _sync_rows(self, node_ids, rows: np.ndarray) -> tuple:
        """Delta-sync the keyed node rows into the resident device
        mirror (host-mirror/dirty-row protocol); returns (dev, n, r)."""
        import jax

        rows = np.asarray(rows, dtype=np.float32)
        n, r = rows.shape
        ids = tuple(node_ids)
        n_pad = self._bucket(n)
        if self._push is None:
            self._push = jax.jit(
                lambda a, rws, vals: a.at[rws].set(vals), donate_argnums=(0,)
            )
        if (
            self._mirror is None
            or ids != self._ids
            or self._mirror.shape != (n_pad, r)
        ):
            self._mirror = np.zeros((n_pad, r), dtype=np.float32)
            self._mirror[:n] = rows
            self._dev = jax.device_put(self._mirror)
            self._ids = ids
        else:
            dirty = np.flatnonzero(np.any(self._mirror[:n] != rows, axis=1))
            if dirty.size:
                from .device import pad_scatter

                self._mirror[dirty] = rows[dirty]
                drows, dvals = pad_scatter(
                    dirty.astype(np.int32), self._mirror[dirty]
                )
                self._dev = self._push(self._dev, drows, dvals)
        return self._dev, n, r

    def pack(self, node_ids, rows, demands: np.ndarray) -> np.ndarray:
        """First-fit ``demands`` onto the keyed node ``rows``; returns
        int32[B] row index per demand (-1 = unfulfilled). ``node_ids``
        key the delta detection — reordered/renamed ids full-sync. This
        is the exact small-batch path, the solver's failure fallback,
        and the differential-test oracle."""
        dev, n, r = self._sync_rows(node_ids, rows)
        b = demands.shape[0]
        b_pad = self._bucket(b, 1)
        dmat = np.zeros((b_pad, r), dtype=np.float32)
        dmat[:b] = demands
        res = bin_pack_residual(dev, dmat)
        nodes = np.asarray(res.node)[:b].copy()
        nodes[nodes >= n] = -1  # a pad row can never really host a demand
        return nodes

    def pack_or_solve(self, node_ids, rows, demands: np.ndarray) -> np.ndarray:
        """``pack`` semantics (int32[B] row per demand, -1 unfulfilled)
        through the projected-gradient solve on big batches: demands
        dedupe to (shape, count) pairs, ``solve_pack_counts`` allocates
        all shapes jointly in a fixed number of batched iterations, and
        the per-demand rows are reassembled rank-by-rank from the
        per-node takes. The O(B) first-fit scan remains the small-batch
        path (cfg.autoscaler_solve_min_demands) and the automatic
        fallback on any solver failure."""
        from ray_tpu.config import cfg

        b = demands.shape[0]
        if (
            not cfg.autoscaler_solve
            or b < int(cfg.autoscaler_solve_min_demands)
        ):
            return self.pack(node_ids, rows, demands)
        try:
            import jax

            dev, n, r = self._sync_rows(node_ids, rows)
            shapes, inverse = np.unique(demands, axis=0, return_inverse=True)
            # extraction is a sequential waterfall over shapes: keep the
            # reference's complex→heavy order (sort_demands) so light
            # shapes cannot strand capacity the heavy ones need
            order = sort_demands(shapes)
            remap = np.empty(len(shapes), dtype=np.int64)
            remap[order] = np.arange(len(shapes))
            shapes = shapes[order]
            inverse = remap[inverse]
            u = shapes.shape[0]
            u_pad = self._bucket(u, 1)
            smat = np.zeros((u_pad, r), dtype=np.float32)
            smat[:u] = shapes
            cvec = np.zeros(u_pad, dtype=np.float32)
            cvec[:u] = np.bincount(inverse, minlength=u)
            iters = max(1, int(cfg.autoscaler_solve_iters))
            res = solve_pack_counts(
                dev, jax.device_put(smat), jax.device_put(cvec), iters=iters
            )
            SOLVER_RUNS.inc()
            SOLVER_ITERS.set(iters)
            # pad node rows hold zero avail (cap 0): clamp to real rows
            per_node = np.asarray(res.per_node)[:u, :n].astype(np.int64)
            out = np.full(b, -1, dtype=np.int32)
            node_idx = np.arange(n)
            # members-by-shape via ONE stable argsort + prefix slicing —
            # a per-shape flatnonzero(inverse == uu) scan would be
            # O(U·B), re-introducing the per-tick host cost the solve
            # path exists to remove
            member_order = np.argsort(inverse, kind="stable")
            counts_b = np.bincount(inverse, minlength=u)
            starts = np.concatenate(([0], np.cumsum(counts_b)[:-1]))
            for uu in range(u):
                members = member_order[
                    starts[uu]: starts[uu] + counts_b[uu]
                ]
                node_rows = np.repeat(node_idx, per_node[uu])
                k = min(node_rows.shape[0], members.shape[0])
                if k:
                    out[members[:k]] = node_rows[:k]
            return out
        except Exception:  # noqa: BLE001 - greedy oracle must keep scaling
            logger.exception("autoscaler solve failed; first-fit fallback")
            SOLVER_FALLBACKS.inc()
            return self.pack(node_ids, rows, demands)


def pick_best_node_type(scores: TypeScore) -> int:
    """Lexicographic argmax over (gpu_ok, num_matching, min_util, mean_util);
    -1 if no type is feasible. Host-side: T is small."""
    f = np.asarray(scores.feasible)
    if not f.any():
        return -1
    key = np.stack(
        [
            np.asarray(scores.gpu_ok, dtype=np.float64),
            np.asarray(scores.num_matching, dtype=np.float64),
            np.asarray(scores.min_util, dtype=np.float64),
            np.asarray(scores.mean_util, dtype=np.float64),
        ],
        axis=1,
    )
    key[~f] = -np.inf
    # np.lexsort sorts ascending by last key primary; we want max.
    order = np.lexsort((key[:, 3], key[:, 2], key[:, 1], key[:, 0]))
    return int(order[-1])
