"""Pipelined scheduling rounds: dispatch and readback on separate threads.

The synchronous round (pre-ISSUE-6) interleaved four phases on one
thread: host prep → upload/dispatch → blocking readback → grant fan-out.
The kernel and the device→host copy are async on every XLA backend, so
the readback wait and the per-grant Python bookkeeping were dead time on
the dispatch path — the delivered scheduler throughput was capped at
1/(sum of all four) even though the phases use disjoint resources.

``SchedulerPipeline`` is the request queue between them:

  scheduler thread                 completion thread
  ────────────────                 ─────────────────
  prep batch N+2                   rows = pending[N].result()  (readback)
  sync + dispatch N+2  ──submit──▶ on_complete(ctx, rows)      (grants)
  prep batch N+3                   rows = pending[N+1].result()
  ...                              ...

Rounds complete strictly in dispatch order (the donated avail chain makes
order the semantics). ``depth`` bounds rounds in flight — submit blocks
when the completion thread falls behind, so the host mirror's lag (and a
grant's worst-case queue latency) stays bounded. ``flush()`` drains the
queue for barrier callers (tests, shutdown, mode switches).

Error contract: an ``on_complete`` raise is caught, logged, and reported
through ``on_error`` (the head respills that round's specs back to its
pending queue) — one poisoned round must not kill the completion thread.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class SchedulerPipeline:
    """Bounded in-order completion queue for dispatched scheduling rounds."""

    def __init__(
        self,
        on_complete: Callable,          # (ctx, rows, round_ms) -> None
        on_error: Optional[Callable] = None,  # (ctx, exc) -> None
        depth: Optional[int] = None,
    ):
        if depth is None:
            from ray_tpu.config import cfg

            depth = max(1, int(cfg.sched_pipeline_depth))
        self.depth = depth
        self._on_complete = on_complete
        self._on_error = on_error
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._stopped = False
        self._waiting = 0  # submitters parked in backpressure
        self._inflight_peak = 0
        self.completed = 0
        self._thread = threading.Thread(
            target=self._drain, name="sched-pipeline", daemon=True
        )
        self._thread.start()

    # -- submit side ----------------------------------------------------

    def submit(self, round_) -> None:
        """Enqueue a dispatched PendingRound for completion; blocks while
        ``depth`` rounds are already awaiting readback (backpressure —
        the dispatch side must not outrun the grant side unboundedly)."""
        with self._cv:
            # counted while parked in backpressure so flush()'s "everything
            # submitted has completed" covers a submitter about to append
            # (a completion wakes flush and the parked submit together —
            # without the count, flush could observe the queue momentarily
            # empty and return before the woken submit appends its round)
            self._waiting += 1
            try:
                while len(self._q) >= self.depth and not self._stopped:
                    self._cv.wait(timeout=0.5)
                if self._stopped:
                    raise RuntimeError("scheduler pipeline stopped")
                self._q.append(round_)
            finally:
                self._waiting -= 1
            self._inflight_peak = max(self._inflight_peak, len(self._q))
            self._cv.notify_all()

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every submitted round — including rounds whose
        submit() is still parked in backpressure — has completed (or
        timeout)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while (self._q or self._waiting) and time.monotonic() < deadline:
                self._cv.wait(timeout=0.1)
            return not (self._q or self._waiting)

    def inflight(self) -> int:
        with self._cv:
            return len(self._q)

    def stats(self) -> dict:
        with self._cv:
            return {
                "depth": self.depth,
                "inflight": len(self._q),
                "inflight_peak": self._inflight_peak,
                "completed": self.completed,
            }

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)

    # -- completion side ------------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait(timeout=0.5)
                if not self._q:
                    if self._stopped:
                        return
                    continue
                round_ = self._q[0]  # keep queued until completed: flush()
                # and inflight() must count rounds whose grants are still
                # being fanned out, not only unread ones
            try:
                rows = round_.result()
                round_ms = (time.perf_counter() - round_.dispatched_at) * 1e3
                self._on_complete(round_.ctx, rows, round_ms)
            except Exception as exc:  # noqa: BLE001 - round must not kill us
                logger.exception("scheduler round completion failed")
                if self._on_error is not None:
                    try:
                        self._on_error(round_.ctx, exc)
                    except Exception:  # noqa: BLE001
                        logger.exception("scheduler round error handler failed")
            with self._cv:
                self._q.popleft()
                self.completed += 1
                self._cv.notify_all()
