"""Batched hybrid scheduling policy as JAX programs.

Reimplements the semantics of the reference's HybridSchedulingPolicy
(/root/reference/src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.cc:96-221)
TPU-first: instead of an O(nodes) hash-map scan per lease request, a whole
*batch* of pending requests is placed by one compiled XLA program over dense
``[nodes, resources]`` arrays.

Two kernels:

- ``hybrid_schedule_batch`` — fidelity mode. ``lax.scan`` over requests,
  deducting availability between steps, preserving the reference's greedy
  request-by-request semantics exactly (two-tier available/feasible selection,
  spread-threshold-zeroed critical utilization score, preferred-node priority,
  uniform pick among the top-k lowest scores with node-index tie-breaking,
  accelerator-node avoidance for non-accelerator requests).

- ``hybrid_schedule_rounds`` — throughput mode ("relaxed batch" — the
  north-star kernel). Every pending request picks its best node
  simultaneously; conflicts are resolved by per-node prefix-sum admission in
  request-priority order; unplaced requests retry next round against the
  deducted view. A handful of fused XLA ops per round instead of B sequential
  steps — this is what places 100k requests in milliseconds.

Scoring semantics (hybrid_scheduling_policy.cc:45-52 +
cluster_resource_data.cc:62-77): score(node) = max over {CPU, MEM,
OBJECT_STORE_MEM} of ``1 - available/total`` (skipping zero totals), zeroed
when below ``spread_threshold``; lower is better.

Multi-objective scoring (ISSUE 7 / ROADMAP 1): the production waterfall
kernel (``hybrid_schedule_shapes_multi_impl``) scores each (shape, node)
pair with a weighted sum of FOUR terms instead of the single utilization
scalar — see ``ScoreWeights``:

- **util** — the reference-compatible spread-threshold-zeroed critical
  utilization above, quantized to 1/16 steps (``quantize_score``).
- **het** — heterogeneity (Gavel, arxiv 2008.09213): a per-(shape,
  node-type) effective-throughput penalty derived from the resident
  per-type per-resource throughput factors (``ClusterView.type_throughput``)
  — 0 on the best type for the shape, →1 on types that run it slowest.
- **frag** — fragmentation (arxiv 2512.10980): the post-placement
  stranded-capacity estimate — the fraction of the node's capacity that
  placing this request would leave free but unable to host the round's
  REFERENCE (largest) demand shape. Penalizes exactly the placement that
  flips a large-capable node into a stranded one, so small requests pack
  instead of spraying.
- **starve** — fairness: per-shape wait-age (rounds parked, normalized by
  ``sched_starve_rounds``) uploaded with the demand rows discounts the
  soft het/frag penalties of long-waiting shapes (``1/(1+w·age)``), so a
  starving shape takes ANY available node rather than holding out for a
  "good" one. Ages ≥ 1.0 additionally arm preemption nomination.

``weights=(1,0,0,0)`` (the default) short-circuits every extra term at
trace time and reproduces the single-objective kernel bit-for-bit — PR
6's sync/pipelined divergence checks keep pinning equivalence.

Preemption nomination: a starving shape (age ≥ 1.0) with unmet demand
and zero current capacity nominates, per shape, the feasible-by-totals
node with the lowest utilization cost (``ShapesResult.preempt_node``);
the head maps the node to concrete victim leases and kill-and-requeues
through the PR 5 lineage/fate-sharing machinery (cluster/head.py
``_maybe_preempt``).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .resources import CRITICAL_COLUMNS, GPU, TPU

# Comparison tolerance for float32 resource arithmetic. Quantities are
# quantized at 1e-4 (FP_SCALE) host-side; this absorbs f32 rounding only.
_EPS = 1e-5

# Padding demand magnitude (device.py _BIG): pad rows carry this in every
# column so they never place; kernels detect them to mask pads out of the
# fragmentation reference shape.
_BIG_PAD = 1e18

ACCEL_COLUMNS = (GPU, TPU)


class HybridConfig(NamedTuple):
    """Static policy knobs (reference defaults from ray_config_def.h:198-209)."""

    spread_threshold: float = 0.5
    top_k_fraction: float = 0.2
    top_k_absolute: int = 1
    avoid_accel_nodes: bool = True
    require_available: bool = False


class BatchResult(NamedTuple):
    node: jax.Array      # int32[B] chosen node row, -1 = infeasible everywhere
    available: jax.Array  # bool[B] chosen node had the resources now (granted)
    avail_out: jax.Array  # float32[N,R] availability after grants


class ScoreWeights(NamedTuple):
    """Multi-objective scoring weights (cfg sched_w_util/het/frag/starve/
    locality).

    Static under jit (a weight change recompiles, which is the rare
    config-edit path, not the round path); ``(1, 0, 0, 0, 0)`` recovers
    the single-objective kernel exactly — the extra terms are skipped at
    trace time, not multiplied by zero."""

    util: float = 1.0
    het: float = 0.0
    frag: float = 0.0
    starve: float = 0.0
    # data locality (ISSUE 13 / ROADMAP 5): per-(shape, node) bonus for
    # nodes already holding the shape's input bytes — the fragmentation
    # term of arxiv 2512.10980 generalized from stranded slots to
    # stranded BYTES (a reduce placed off its map partitions strands
    # their resident copies behind a cross-node refetch).
    locality: float = 0.0


#: One-sided quantum of the waterfall kernels' utilization score: the ONE
#: definition of the shapes/ring-path tie-break. Scores are floored to
#: 1/QUANTIZE_STEPS buckets and a per-node uniform jitter in [0, 1) picks
#: uniformly inside a bucket — near-tied nodes (score gap < 1/16) look
#: identical, mirroring the per-task path's uniform pick among the top-k.
#: The per-task kernel (``_pick_topk``) instead sorts EXACT scores and
#: randomizes among the first k — an intentional divergence documented in
#: COMPONENTS.md (scheduling plane): the waterfall has no per-request k.
QUANTIZE_STEPS = 16.0


def quantize_score(score: jax.Array) -> jax.Array:
    """Bucketized utilization score shared by the shapes path, the ring
    path, and the multi-objective cost (keeps all waterfall consumers
    tie-breaking identically)."""
    return jnp.floor(score * QUANTIZE_STEPS)


def _critical_score(totals: jax.Array, avail: jax.Array, threshold: float) -> jax.Array:
    """float32[N] spread-threshold-zeroed critical resource utilization."""
    t = totals[:, CRITICAL_COLUMNS,]
    a = avail[:, CRITICAL_COLUMNS,]
    util = jnp.where(t > 0, 1.0 - a / jnp.where(t > 0, t, 1.0), 0.0)
    score = jnp.max(util, axis=1)
    return jnp.where(score < threshold, 0.0, score)


def _shape_capacity(
    totals: jax.Array,     # f32[N,R]
    avail_run: jax.Array,  # f32[N,R]
    alive: jax.Array,      # bool[N]
    d: jax.Array,          # f32[R] one demand shape
) -> tuple:
    """(cap f32[N], has_demand bool[], feas bool[N]): how many requests of
    shape ``d`` each node can absorb right now (inf for a zero-demand
    shape on a feasible node; 0 on dead/infeasible nodes), plus the
    totals-feasibility mask (preemption nomination needs nodes that COULD
    host the shape if their current usage were reclaimed). The ONE
    definition of per-node shape capacity — the round kernel, the
    parked-ring kernel, and the unpark slot estimator must
    deduct/estimate with identical math or the host mirror's convergence
    accounting drifts."""
    feas = alive & jnp.all(totals >= d[None, :] - _EPS, axis=1)
    demanded = d > 0
    ratio = jnp.where(
        demanded[None, :],
        jnp.floor((avail_run + _EPS) / jnp.where(demanded, d, 1.0)[None, :]),
        jnp.inf,
    )
    cap = jnp.min(ratio, axis=1)  # [N] how many fit
    has_demand = jnp.any(demanded)
    cap = jnp.where(has_demand, cap, jnp.inf)  # zero-demand: no cap
    cap = jnp.where(feas, jnp.maximum(cap, 0.0), 0.0)
    return cap, has_demand, feas


def _het_penalty(
    d: jax.Array,       # f32[R] one demand shape
    ntypes: jax.Array,  # int32[N] node-type id per node
    thr: jax.Array,     # f32[T,R] per-type per-resource throughput factors
) -> jax.Array:
    """f32[N] heterogeneity penalty in [0, 1]: 1 - (this node type's
    effective throughput for the shape) / (the best type's). The
    per-(shape, node-type) throughput matrix of Gavel (arxiv 2008.09213),
    stored in its resident factorized form: ``thr[t, c]`` = relative
    throughput of resource column ``c`` on node type ``t``
    (resources.py ClusterView.type_throughput). A shape's effective
    throughput on a type is its demand-weighted mean factor."""
    dsum = jnp.maximum(jnp.sum(d), _EPS)
    tput = thr @ d / dsum                    # f32[T]
    best = jnp.maximum(jnp.max(tput), _EPS)
    pen_t = 1.0 - tput / best                # f32[T], 0 on the best type
    return pen_t[ntypes]


def _frag_penalty(
    totals: jax.Array,     # f32[N,R]
    avail_run: jax.Array,  # f32[N,R]
    d: jax.Array,          # f32[R] the shape being placed
    ref: jax.Array,        # f32[R] the round's reference (largest) shape
) -> jax.Array:
    """f32[N] post-placement stranded-capacity estimate in [0, 1]
    (arxiv 2512.10980): the fraction of a node's capacity (over the
    reference shape's demanded columns) that placing one ``d`` would
    leave free but unable to host the reference shape. Nodes that
    already cannot host ``ref`` strand only their (small) remaining free
    fraction; a placement that FLIPS a large-capable node to stranded
    pays its whole free fraction — so small shapes fill already-broken
    nodes before breaking whole ones."""
    after = avail_run - d[None, :]
    ref_cols = ref > 0
    fits_ref = jnp.all(
        jnp.where(ref_cols[None, :], after >= ref[None, :] - _EPS, True),
        axis=1,
    )
    free = jnp.sum(jnp.where(ref_cols[None, :], jnp.maximum(after, 0.0), 0.0), axis=1)
    total = jnp.maximum(
        jnp.sum(jnp.where(ref_cols[None, :], totals, 0.0), axis=1), _EPS
    )
    return jnp.where(fits_ref, 0.0, free / total)


def _fits(view: jax.Array, demand: jax.Array) -> jax.Array:
    """bool[N]: every resource of ``demand`` fits in ``view`` rows."""
    return jnp.all(view >= demand[None, :] - _EPS - 1e-6 * demand[None, :], axis=1)


def _pick_topk(
    mask: jax.Array,
    score: jax.Array,
    k: int,
    key: jax.Array,
    prefer: jax.Array,
    prefer_ok: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Reference GetBestNode (hybrid_scheduling_policy.cc:62-94): stable-sort
    candidates by (score, node index), prefer the preferred node if its score
    ties the minimum, else uniform among the first k."""
    n = score.shape[0]
    inf = jnp.float32(jnp.inf)
    s = jnp.where(mask, score, inf)
    order = jnp.argsort(s, stable=True)  # ties broken by node index
    num_cand = jnp.sum(mask.astype(jnp.int32))
    kk = jnp.clip(jnp.minimum(jnp.int32(k), num_cand), 1)
    r = jax.random.randint(key, (), 0, kk)
    chosen = order[r]
    best_score = s[order[0]]
    use_prefer = prefer_ok & (score[prefer] <= best_score)
    chosen = jnp.where(use_prefer, prefer, chosen)
    return jnp.where(num_cand > 0, chosen, -1), num_cand > 0


@functools.partial(
    jax.jit,
    static_argnames=("config", "num_candidates"),
)
def hybrid_schedule_batch(
    totals: jax.Array,        # f32[N,R]
    avail: jax.Array,         # f32[N,R]
    alive: jax.Array,         # bool[N]
    demands: jax.Array,       # f32[B,R]
    prefer: jax.Array,        # int32[B] preferred (local) node row per request
    force_spill: jax.Array,   # bool[B] avoid_local_node
    seed: jax.Array,          # uint32 scalar
    *,
    config: HybridConfig = HybridConfig(),
    num_candidates: Optional[int] = None,
) -> BatchResult:
    """Greedy-faithful batched hybrid scheduling (see module docstring)."""
    n = totals.shape[0]
    k = num_candidates or max(
        config.top_k_absolute, int(n * config.top_k_fraction)
    )
    base_key = jax.random.PRNGKey(seed)

    accel_free = jnp.all(
        totals[:, ACCEL_COLUMNS,] <= 0, axis=1
    )  # nodes with no accelerators at all

    def step(avail_run, xs):
        demand, pref, spill, i = xs
        key = jax.random.fold_in(base_key, i)
        feas = alive & _fits(totals, demand)
        availm = feas & _fits(avail_run, demand)
        score = _critical_score(totals, avail_run, config.spread_threshold)
        cand_mask_base = jnp.where(spill, jnp.arange(n) != pref, True)

        wants_accel = jnp.any(demand[ACCEL_COLUMNS,] > 0)

        def tiered(avail_mask, feas_mask, require_avail):
            m1 = avail_mask & cand_mask_base
            p_ok1 = ~spill & avail_mask[pref]
            c1, v1 = _pick_topk(m1, score, k, key, pref, p_ok1)
            m2 = feas_mask & ~avail_mask & cand_mask_base
            p_ok2 = ~spill & feas_mask[pref]
            c2, v2 = _pick_topk(m2, score, k, key, pref, p_ok2)
            use2 = ~v1 & ~require_avail
            node = jnp.where(v1, c1, jnp.where(use2, c2, -1))
            granted = v1
            return node, granted

        # Pass 1 (non-accel requests only): schedule on accelerator-free
        # nodes, require availability (hybrid_scheduling_policy.cc:196-211).
        node_a, granted_a = tiered(
            availm & accel_free, feas & accel_free, jnp.bool_(True)
        )
        # Pass 2: any node.
        node_b, granted_b = tiered(
            availm, feas, jnp.bool_(config.require_available)
        )
        use_a = config.avoid_accel_nodes & ~wants_accel & (node_a >= 0)
        node = jnp.where(use_a, node_a, node_b)
        granted = jnp.where(use_a, granted_a, granted_b) & (node >= 0)

        safe_node = jnp.maximum(node, 0)
        deduction = jnp.where(granted, demand, 0.0)
        avail_run = avail_run.at[safe_node].add(-deduction)
        return avail_run, (node, granted)

    b = demands.shape[0]
    avail_out, (nodes, granted) = jax.lax.scan(
        step,
        avail,
        (demands, prefer, force_spill, jnp.arange(b, dtype=jnp.uint32)),
    )
    return BatchResult(nodes.astype(jnp.int32), granted, avail_out)


class RoundsResult(NamedTuple):
    node: jax.Array      # int32[B], -1 = unplaced after all rounds
    avail_out: jax.Array  # f32[N,R]


@functools.partial(jax.jit, static_argnames=("rounds", "spread_threshold"))
def hybrid_schedule_rounds(
    totals: jax.Array,   # f32[N,R]
    avail: jax.Array,    # f32[N,R]
    alive: jax.Array,    # bool[N]
    demands: jax.Array,  # f32[B,R]
    seed: jax.Array,
    *,
    rounds: int = 8,
    spread_threshold: float = 0.5,
) -> RoundsResult:
    """Throughput-mode placement: simultaneous choice + prefix-sum admission.

    Each round: (1) score all nodes once; (2) every pending request picks its
    cheapest feasible-and-available node (random jitter decorrelates ties so
    requests spread over equally-scored nodes); (3) requests are admitted
    against each node's availability in request order via a grouped exclusive
    prefix sum; (4) admitted demands are deducted with one segment-sum.
    Converges to the greedy fixed point in a few rounds; leftover requests
    report -1 (queue/spill — the caller's ClusterLeaseManager analog retries).
    """
    n, r = totals.shape
    b = demands.shape[0]
    base_key = jax.random.PRNGKey(seed)

    feas = alive[None, :] & jnp.all(
        totals[None, :, :] >= demands[:, None, :] * (1 + 1e-6) - _EPS, axis=2
    )  # bool[B,N] — feasibility is static across rounds

    def round_body(i, state):
        assigned, avail_run = state
        pending = assigned < 0
        score = _critical_score(totals, avail_run, spread_threshold)  # [N]
        fits = jnp.all(
            avail_run[None, :, :] >= demands[:, None, :] - _EPS, axis=2
        )  # [B,N]
        cand = feas & fits & pending[:, None]
        # Per-(request, node) jitter in [0, 1e-3): random tie-break, like the
        # reference's uniform pick among equal-score top-k.
        key = jax.random.fold_in(base_key, i)
        jitter = jax.random.uniform(key, (b, n), dtype=jnp.float32) * 1e-3
        cost = jnp.where(cand, score[None, :] + jitter, jnp.inf)
        choice = jnp.argmin(cost, axis=1).astype(jnp.int32)
        has_cand = jnp.any(cand, axis=1)
        choice = jnp.where(has_cand & pending, choice, n)  # n = dummy segment

        # Admission: group requests by chosen node, exclusive prefix-sum of
        # demands within each group (request order = priority order).
        order = jnp.argsort(choice, stable=True)
        c_sorted = choice[order]
        d_sorted = demands[order]
        csum = jnp.cumsum(d_sorted, axis=0)
        is_start = jnp.concatenate(
            [jnp.array([True]), c_sorted[1:] != c_sorted[:-1]]
        )
        base = jnp.where(is_start[:, None], csum - d_sorted, 0.0)
        base = jax.lax.cummax(base, axis=0)  # propagate group base downward
        prefix_excl = csum - d_sorted - base
        node_avail = avail_run[jnp.minimum(c_sorted, n - 1)]
        ok = jnp.all(prefix_excl + d_sorted <= node_avail + _EPS, axis=1)
        ok = ok & (c_sorted < n)

        used = jax.ops.segment_sum(
            jnp.where(ok[:, None], d_sorted, 0.0), c_sorted, num_segments=n + 1
        )[:n]
        avail_run = avail_run - used
        new_assigned = assigned.at[order].max(
            jnp.where(ok, c_sorted, -1).astype(jnp.int32)
        )
        return new_assigned, avail_run

    assigned0 = jnp.full((b,), -1, dtype=jnp.int32)
    assigned, avail_out = jax.lax.fori_loop(
        0, rounds, round_body, (assigned0, avail)
    )
    return RoundsResult(assigned, avail_out)


@functools.partial(jax.jit, static_argnames=("rounds",))
def hybrid_schedule_rounds_chunked(
    totals: jax.Array,    # f32[N,R]
    avail: jax.Array,     # f32[N,R]
    alive: jax.Array,     # bool[N]
    demands: jax.Array,   # f32[C,B,R] — C chunks of B requests
    seed: jax.Array,
    *,
    rounds: int = 4,
) -> RoundsResult:
    """Chunked throughput mode: one device dispatch places C·B requests.

    Chunks run greedily in sequence (each sees the previous chunks'
    deductions — same semantics as feeding the queue in batches), but the
    whole loop is a single compiled lax.scan: no host round-trips between
    chunks. This is the kernel the 100k-task benchmark drives.
    """

    def body(avail_run, xs):
        chunk, i = xs
        res = hybrid_schedule_rounds(
            totals, avail_run, alive, chunk, seed + i, rounds=rounds
        )
        return res.avail_out, res.node

    c = demands.shape[0]
    avail_out, nodes = jax.lax.scan(
        body, avail, (demands, jnp.arange(c, dtype=jnp.uint32))
    )
    return RoundsResult(nodes.reshape(-1), avail_out)


class ShapesResult(NamedTuple):
    node: jax.Array          # int32[B], -1 = unplaced
    avail_out: jax.Array     # f32[N,R]
    # int32[U] per-shape preemption nomination: the feasible-by-totals
    # node with the lowest utilization cost, for starving (age >= 1.0)
    # shapes with unmet demand and zero current capacity; -1 = none.
    preempt_node: jax.Array
    # f32[B, 5] per-request cost attribution at the WINNING node
    # (explain=True only; a [1, 5] zero placeholder otherwise):
    # columns = (util, het, frag, locality, starve-discount) — the
    # weighted contributions exactly as they entered the cost, plus the
    # starvation discount scale applied to the soft terms. Rows of
    # unplaced requests are zero.
    terms: jax.Array


#: ``ShapesResult.terms`` column order — the ONE naming of the decision
#: attribution vector, shared by the kernel, the head's explanation
#: table, and the Chrome-trace export.
TERM_NAMES = ("util", "het", "frag", "locality", "starve_discount")


def _shape_cost(
    totals: jax.Array,
    avail_run: jax.Array,
    d: jax.Array,
    cap: jax.Array,
    score: jax.Array,
    jitter: jax.Array,
    age: jax.Array,
    ntypes: jax.Array,
    thr: jax.Array,
    ref: jax.Array,
    weights: ScoreWeights,
    loc: Optional[jax.Array] = None,
    want_terms: bool = False,
):
    """f32[N] multi-objective placement cost for one shape (lower is
    better; inf on nodes with no capacity). The ONE cost definition
    shared by the shapes waterfall and the parked-ring kernel. Weight
    terms are skipped at TRACE time when their weight is 0, so
    weights=(1,0,0,0,0) emits exactly the single-objective program.

    ``loc``: optional f32[N] locality fraction in [0, 1] — the share of
    this shape's input bytes already resident on each node (normalized
    host-side). A BONUS, not a penalty: all-zero rows (no located
    inputs, or a consumer with no locality data like the parked ring)
    leave the cost untouched, so locality-blind shapes keep the exact
    single-objective ordering even at weight > 0.

    ``want_terms`` (decision attribution, ISSUE 15): additionally
    return f32[5, N] per-node term vectors in ``TERM_NAMES`` order —
    each weighted contribution exactly as it entered the cost (locality
    negative: it is a bonus), row 4 the starvation discount scale. The
    cost composition itself is op-for-op identical either way, so the
    explain variant places bit-identically."""
    cost = quantize_score(score)
    if weights.util != 1.0:
        cost = weights.util * cost
    util_c = cost
    n = score.shape[0]
    zeros = jnp.zeros((n,), dtype=jnp.float32) if want_terms else None
    het_c = frag_c = loc_c = zeros
    scale = 1.0
    has_loc = bool(weights.locality) and loc is not None
    if weights.het or weights.frag or has_loc:
        # starving shapes discount the soft terms: a shape that has
        # waited w_starve-scaled ages takes ANY available node
        scale = 1.0 / (1.0 + weights.starve * age) if weights.starve else 1.0
        if weights.het:
            het_c = (QUANTIZE_STEPS * weights.het * scale) * _het_penalty(
                d, ntypes, thr
            )
            cost = cost + het_c
        if weights.frag:
            frag_c = (QUANTIZE_STEPS * weights.frag * scale) * _frag_penalty(
                totals, avail_run, d, ref
            )
            cost = cost + frag_c
        if has_loc:
            # discounting the bonus too: a starving shape stops holding
            # out for the partition-heavy node and takes any capacity
            loc_c = (QUANTIZE_STEPS * weights.locality * scale) * loc
            cost = cost - loc_c
    cost = cost + jitter
    cost = jnp.where(cap > 0, cost, jnp.inf)
    if not want_terms:
        return cost
    terms = jnp.stack(
        [
            util_c,
            het_c,
            frag_c,
            -loc_c,  # as it entered the cost (a bonus is negative)
            jnp.full((n,), scale, dtype=jnp.float32),
        ]
    )
    return cost, terms


def _nominate_preemption(
    feas: jax.Array,
    cap: jax.Array,
    score: jax.Array,
    jitter: jax.Array,
    age: jax.Array,
    unmet: jax.Array,
) -> jax.Array:
    """int32 nominated victim node for one shape (-1 = none): starving
    (age >= 1.0) + unmet demand + zero capacity anywhere → the
    feasible-by-totals node with the lowest exact utilization score
    (lowest-cost reclaim; jitter decorrelates ties across shapes)."""
    cand = feas & (cap <= 0)
    pscore = jnp.where(cand, score + jitter, jnp.inf)
    pn = jnp.argmin(pscore).astype(jnp.int32)
    ok = (age >= 1.0) & unmet & jnp.any(cand)
    return jnp.where(ok, pn, jnp.int32(-1))


def _reference_shape(shape_rows: jax.Array, real: jax.Array) -> jax.Array:
    """f32[R] per-column envelope of the round's REAL demand shapes — the
    'largest demand' the fragmentation term protects capacity for.
    ``real`` masks padding rows (_BIG demands / empty ring slots)."""
    return jnp.max(jnp.where(real[:, None], shape_rows, 0.0), axis=0)


def hybrid_schedule_shapes_multi_impl(
    totals: jax.Array,        # f32[N,R]
    avail: jax.Array,         # f32[N,R]
    alive: jax.Array,         # bool[N]
    ntypes: jax.Array,        # int32[N] node-type id per node
    thr: jax.Array,           # f32[T,R] per-type resource throughput factors
    shape_demands: jax.Array,  # f32[U,R] unique demand shapes, priority order
    shape_ids: jax.Array,     # int32[B] shape index per request
    ages: jax.Array,          # f32[U] normalized wait-age per shape
    seed: jax.Array,
    *,
    spread_threshold: float = 0.5,
    weights: ScoreWeights = ScoreWeights(),
    preempt: bool = False,
    locality: Optional[jax.Array] = None,
    explain: bool = False,
) -> ShapesResult:
    """Shape-grouped waterfall placement — the fastest scheduling kernel.

    ``locality``: optional f32[U, N] per-shape per-node locality fraction
    (share of the shape's input bytes resident on each node, normalized
    host-side; see ``_shape_cost``). Consulted only when
    ``weights.locality`` > 0 — None keeps the pre-locality trace.

    The reference queues leases per *scheduling class* (shape) and schedules
    shape-by-shape (cluster_lease_manager.cc:196 iterates shape queues); this
    ``explain`` (static): additionally accumulate each placed request's
    per-term cost attribution at its winning node
    (``ShapesResult.terms``, see ``TERM_NAMES``) — one extra f32[B, 5]
    carry through the scan plus a gather per shape; the placement math
    (including RNG consumption) is untouched, so explain=True places
    bit-identically to explain=False.

    kernel keeps that structure but places every request of a shape at once:

      for each shape u (sequential scan, hardest shapes first):
        capacity[n] = how many u-requests node n can still absorb (exact,
                      elementwise floor(avail/demand))
        order nodes by the multi-objective cost (``_shape_cost``:
        quantized utilization + heterogeneity + fragmentation, starvation-
        discounted, + jitter)                             # top-k-ish spread
        request with rank r inside the shape  →  first node whose cumulative
        capacity exceeds r (vectorized searchsorted)
        deduct per-node counts with one segment_sum

    O(U·(N log N + B log N)) with no [B,N] intermediate — places 100k
    requests on 1k nodes in ~1 ms on one TPU chip. Conflict-free and
    capacity-exact by construction; semantics match greedy filling of
    best-scored nodes within each shape class. With ``preempt`` the scan
    additionally nominates one victim node per starving unmet shape
    (``ShapesResult.preempt_node``) — placements are unaffected.
    """
    n = totals.shape[0]
    b = shape_ids.shape[0]
    u = shape_demands.shape[0]
    base_key = jax.random.PRNGKey(seed)

    if weights.frag:
        real = jnp.all(shape_demands < _BIG_PAD * 0.5, axis=1)
        ref = _reference_shape(shape_demands, real)
    else:
        ref = jnp.zeros((shape_demands.shape[1],), dtype=jnp.float32)

    # rank of each request within its shape class
    order = jnp.argsort(shape_ids, stable=True)
    sorted_ids = shape_ids[order]
    idx = jnp.arange(b)
    is_start = jnp.concatenate(
        [jnp.array([True]), sorted_ids[1:] != sorted_ids[:-1]]
    )
    group_start = jax.lax.cummax(jnp.where(is_start, idx, 0))
    rank_sorted = idx - group_start  # rank within shape, in sorted order

    def per_shape(carry, uidx):
        avail_run, terms_acc = carry
        d = shape_demands[uidx]
        cap, has_demand, feas = _shape_capacity(totals, avail_run, alive, d)
        score = _critical_score(totals, avail_run, spread_threshold)
        key = jax.random.fold_in(base_key, uidx)
        # quantized score + random jitter == uniform pick among near-tied
        # nodes (the reference's top-k randomization)
        jitter = jax.random.uniform(key, (n,), dtype=jnp.float32)
        loc_row = (
            locality[uidx]
            if (weights.locality and locality is not None)
            else None
        )
        tvec = None
        if explain:
            cost, tvec = _shape_cost(
                totals, avail_run, d, cap, score, jitter,
                ages[uidx], ntypes, thr, ref, weights, loc_row,
                want_terms=True,
            )
        else:
            cost = _shape_cost(
                totals, avail_run, d, cap, score, jitter,
                ages[uidx], ntypes, thr, ref, weights, loc_row,
            )
        # top-k beats a full argsort ~3x on CPU XLA and is exact here: a
        # request at rank r within its shape needs at most r+1 nodes of
        # the cost order, ranks are < b <= k, and every cap>0 node sorts
        # ahead of the cost=inf (cap=0) ones — so the k cheapest nodes
        # cover every placement the full order could make.
        k = min(n, b)
        _, node_order = jax.lax.top_k(-cost, k)
        cap_sorted = cap[node_order]
        cumcap = jnp.cumsum(jnp.where(jnp.isfinite(cap_sorted), cap_sorted, 2.0 * b))
        sel = sorted_ids == uidx
        pos = jnp.searchsorted(cumcap, rank_sorted.astype(cumcap.dtype), side="right")
        valid = sel & (rank_sorted < cumcap[-1]) & (pos < k)
        safe_pos = jnp.minimum(pos, k - 1)
        node_u = jnp.where(valid, node_order[safe_pos], -1)
        counts = jax.ops.segment_sum(
            jnp.where(valid, 1.0, 0.0),
            jnp.where(valid, node_u, n),
            num_segments=n + 1,
        )[:n]
        avail_run = jnp.where(
            has_demand, avail_run - counts[:, None] * d[None, :], avail_run
        )
        if explain:
            # attribution gather: every request this shape placed takes
            # the [5] term column of its winning node (exactly one shape
            # writes any request's row, so summing into the carry is a
            # scatter, not an accumulation)
            safe_node = jnp.maximum(node_u, 0)
            contrib = jnp.where(
                valid[:, None], tvec[:, safe_node].T, 0.0
            )  # f32[B, 5] in sorted-request order
            terms_acc = terms_acc + contrib
        if preempt:
            unmet = jnp.sum(sel) > jnp.sum(valid)
            pre_u = _nominate_preemption(
                feas, cap, score, jitter, ages[uidx], unmet
            )
        else:
            pre_u = jnp.int32(-1)
        return (avail_run, terms_acc), (node_u, pre_u)

    terms0 = (
        jnp.zeros((b, 5), dtype=jnp.float32)
        if explain
        else jnp.zeros((1, 5), dtype=jnp.float32)
    )
    (avail_out, terms_sorted), (nodes_per_shape, preempt_nodes) = jax.lax.scan(
        per_shape, (avail, terms0), jnp.arange(u, dtype=jnp.int32)
    )
    nodes_sorted = jnp.max(nodes_per_shape, axis=0)  # exactly one shape wrote >=0
    nodes = jnp.full((b,), -1, dtype=jnp.int32).at[order].set(
        nodes_sorted.astype(jnp.int32)
    )
    if explain:
        # back to original request order (rows of unplaced requests are 0)
        terms = jnp.zeros((b, 5), dtype=jnp.float32).at[order].set(
            terms_sorted
        )
    else:
        terms = terms0
    return ShapesResult(nodes, avail_out, preempt_nodes, terms)


def hybrid_schedule_shapes_impl(
    totals: jax.Array,        # f32[N,R]
    avail: jax.Array,         # f32[N,R]
    alive: jax.Array,         # bool[N]
    shape_demands: jax.Array,  # f32[U,R] unique demand shapes, priority order
    shape_ids: jax.Array,     # int32[B] shape index per request
    seed: jax.Array,
    *,
    spread_threshold: float = 0.5,
) -> RoundsResult:
    """Single-objective waterfall (the pre-ISSUE-7 signature): the multi
    kernel at weights=(1,0,0,0) with homogeneous node types — emits the
    identical XLA program (extra terms skip at trace time)."""
    res = hybrid_schedule_shapes_multi_impl(
        totals,
        avail,
        alive,
        jnp.zeros((totals.shape[0],), dtype=jnp.int32),
        jnp.ones((1, totals.shape[1]), dtype=jnp.float32),
        shape_demands,
        shape_ids,
        jnp.zeros((shape_demands.shape[0],), dtype=jnp.float32),
        seed,
        spread_threshold=spread_threshold,
    )
    return RoundsResult(res.node, res.avail_out)


# Public jitted entry points; DeviceSchedulerState jits the multi impl to
# keep scheduler state (including node types + throughput factors)
# resident across rounds.
hybrid_schedule_shapes = functools.partial(
    jax.jit, static_argnames=("spread_threshold",)
)(hybrid_schedule_shapes_impl)

hybrid_schedule_shapes_multi = functools.partial(
    jax.jit,
    static_argnames=("spread_threshold", "weights", "preempt", "explain"),
)(hybrid_schedule_shapes_multi_impl)


class RingResult(NamedTuple):
    placed: jax.Array    # int32[S] requests placed per ring slot
    per_node: jax.Array  # int32[S,N] placements per node per slot
    avail_out: jax.Array  # f32[N,R]
    preempt_node: jax.Array  # int32[S] nominated victim node per slot, -1=none


def ring_schedule_impl(
    totals: jax.Array,       # f32[N,R]
    avail: jax.Array,        # f32[N,R]
    alive: jax.Array,        # bool[N]
    ntypes: jax.Array,       # int32[N] node-type id per node
    thr: jax.Array,          # f32[T,R] per-type resource throughput factors
    ring_shapes: jax.Array,  # f32[S,R] parked demand shapes (device-resident)
    counts: jax.Array,       # int32[S] pending requests per shape
    ages: jax.Array,         # f32[S] normalized wait-age per ring slot
    seed: jax.Array,
    *,
    spread_threshold: float = 0.5,
    weights: ScoreWeights = ScoreWeights(),
    preempt: bool = False,
) -> RingResult:
    """Count-driven waterfall over the parked-demand ring.

    Same placement math as ``hybrid_schedule_shapes_multi_impl`` (per-shape
    node capacity, the shared multi-objective ``_shape_cost`` node
    ordering, cumulative-capacity fill), but demand arrives as (resident
    shape row, count) pairs instead of per-request rows —
    repeatedly-unplaceable shapes retry without re-uploading a demand
    matrix or shape-id vector, and the readback is per-node placement
    COUNTS (the caller assigns its FIFO-parked specs to nodes
    rank-by-rank), not per-request rows. Parked shapes are where
    starvation lives, so the ring nominates preemption victims exactly
    like the round kernel.
    """
    n = totals.shape[0]
    s = ring_shapes.shape[0]
    base_key = jax.random.PRNGKey(seed)

    if weights.frag:
        ref = _reference_shape(ring_shapes, counts > 0)
    else:
        ref = jnp.zeros((ring_shapes.shape[1],), dtype=jnp.float32)

    def per_shape(avail_run, uidx):
        d = ring_shapes[uidx]
        want = counts[uidx].astype(jnp.float32)
        cap, has_demand, feas = _shape_capacity(totals, avail_run, alive, d)
        score = _critical_score(totals, avail_run, spread_threshold)
        key = jax.random.fold_in(base_key, uidx)
        jitter = jax.random.uniform(key, (n,), dtype=jnp.float32)
        cost = _shape_cost(
            totals, avail_run, d, cap, score, jitter,
            ages[uidx], ntypes, thr, ref, weights,
        )
        node_order = jnp.argsort(cost)
        cap_sorted = cap[node_order]
        # zero-demand shapes have infinite per-node capacity: the first
        # (cheapest) node absorbs the whole count
        cap_fin = jnp.where(jnp.isfinite(cap_sorted), cap_sorted, want)
        cum_prev = jnp.concatenate(
            [jnp.zeros((1,), cap_fin.dtype), jnp.cumsum(cap_fin)[:-1]]
        )
        take_sorted = jnp.clip(want - cum_prev, 0.0, cap_fin)
        per_node = jnp.zeros((n,), jnp.float32).at[node_order].set(take_sorted)
        avail_run = jnp.where(
            has_demand, avail_run - per_node[:, None] * d[None, :], avail_run
        )
        placed = jnp.sum(take_sorted)
        if preempt:
            pre_u = _nominate_preemption(
                feas, cap, score, jitter, ages[uidx], placed < want
            )
        else:
            pre_u = jnp.int32(-1)
        return avail_run, (
            placed.astype(jnp.int32), per_node.astype(jnp.int32), pre_u
        )

    avail_out, (placed, per_node, preempt_nodes) = jax.lax.scan(
        per_shape, avail, jnp.arange(s, dtype=jnp.int32)
    )
    return RingResult(placed, per_node, avail_out, preempt_nodes)


def shape_slots_impl(
    totals: jax.Array,   # f32[N,R]
    avail: jax.Array,    # f32[N,R]
    alive: jax.Array,    # bool[N]
    shapes: jax.Array,   # f32[S,R]
) -> jax.Array:
    """int32[S]: grantable-slot estimate per demand shape — how many
    requests of each shape the current availability could absorb. The
    device form of the unpark estimator's per-shape host scan
    (scheduler/unpark.py): one batched dispatch over the RESIDENT arrays
    instead of S NumPy passes over a fresh host copy. ``lax.map`` keeps
    the intermediate at [N,R] per shape (no [S,N,R] blow-up at 10k nodes)."""

    def one(d):
        slots, _, _ = _shape_capacity(totals, avail, alive, d)
        # zero-demand shapes report "huge", clamped to int32-safe
        return jnp.minimum(jnp.sum(slots), 2.0**31 - 1).astype(jnp.int32)

    return jax.lax.map(one, shapes)


def hardest_first_order(shape_rows: np.ndarray) -> np.ndarray:
    """Stable shape-priority order (SortRequiredResources semantics): more
    distinct resources first, then heavier. The ONE definition of the
    waterfall kernel's placement order — shared by ``dedupe_shapes`` and
    the head's cached-shape round prep (head._round_shapes), which must
    order identical demand sets identically."""
    return np.lexsort(
        (
            np.arange(shape_rows.shape[0]),
            -shape_rows.sum(axis=1),
            -(shape_rows > 0).sum(axis=1),
        )
    )


def dedupe_shapes(demands: np.ndarray):
    """Host helper: unique demand shapes (priority-sorted hardest-first, like
    SortRequiredResources) + per-request shape ids."""
    uniq, inverse = np.unique(demands, axis=0, return_inverse=True)
    order = hardest_first_order(uniq)
    remap = np.empty(len(uniq), dtype=np.int32)
    remap[order] = np.arange(len(uniq), dtype=np.int32)
    return uniq[order].astype(np.float32), remap[inverse].astype(np.int32)


@jax.jit
def retire_scores_impl(
    totals: jax.Array,   # f32[N,R]
    avail: jax.Array,    # f32[N,R]
    demand: jax.Array,   # f32[N] — solver placements landing on the node
) -> jax.Array:
    """Retirement desirability per node for the elasticity plane: higher
    = retire first. Fully idle beats partially idle (idle fraction),
    small beats big at equal idleness (losing a small node costs the
    least future headroom), and any node the solve placed demand on is
    pushed far negative — the controller must never retire a machine the
    same tick's solve just counted on."""
    cap = jnp.maximum(totals.sum(axis=1), _EPS)
    idle_frac = avail.sum(axis=1) / cap
    size_bias = cap / jnp.maximum(jnp.max(cap), _EPS)
    return idle_frac - 0.5 * size_bias - 1e6 * (demand > 0)


def retire_order(
    totals: np.ndarray, avail: np.ndarray, demand: np.ndarray
) -> np.ndarray:
    """Host wrapper: node indices best-retire-first. Falls back to the
    equivalent NumPy scoring when the backend is unavailable."""
    try:
        scores = np.asarray(
            retire_scores_impl(
                jnp.asarray(totals, dtype=jnp.float32),
                jnp.asarray(avail, dtype=jnp.float32),
                jnp.asarray(demand, dtype=jnp.float32),
            )
        )
    except Exception:  # noqa: BLE001 - scoring is host-recoverable
        cap = np.maximum(totals.sum(axis=1), 1e-9)
        idle_frac = avail.sum(axis=1) / cap
        size_bias = cap / max(float(cap.max()), 1e-9)
        scores = idle_frac - 0.5 * size_bias - 1e6 * (demand > 0)
    return np.argsort(-scores, kind="stable")


# ---------------------------------------------------------------------------
# NumPy golden model (host, exact) — used by tests to pin down the batched
# kernels' semantics against an independent implementation of the reference
# behavior, the way the reference pins its policy in
# policy/tests/hybrid_scheduling_policy_test.cc.
# ---------------------------------------------------------------------------


def hybrid_schedule_reference(
    totals: np.ndarray,
    avail: np.ndarray,
    alive: np.ndarray,
    demands: np.ndarray,
    prefer: np.ndarray,
    force_spill: np.ndarray,
    *,
    config: HybridConfig = HybridConfig(),
    rng: Optional[np.random.Generator] = None,
    top_k_override: Optional[int] = None,
):
    """Sequential host implementation of the same semantics (rng=None →
    deterministic: always the single best candidate)."""
    n = totals.shape[0]
    k = top_k_override or max(config.top_k_absolute, int(n * config.top_k_fraction))
    avail = avail.copy()
    out_nodes, out_granted = [], []
    for b in range(demands.shape[0]):
        d = demands[b]
        feas = alive & np.all(totals >= d[None, :] - _EPS, axis=1)
        availm = feas & np.all(avail >= d[None, :] - _EPS, axis=1)
        with np.errstate(divide="ignore", invalid="ignore"):
            t = totals[:, CRITICAL_COLUMNS,]
            a = avail[:, CRITICAL_COLUMNS,]
            util = np.where(t > 0, 1.0 - a / np.where(t > 0, t, 1.0), 0.0)
        score = util.max(axis=1)
        score = np.where(score < config.spread_threshold, 0.0, score)

        def pick(mask, require_avail_unused=None):
            p = int(prefer[b])
            m = mask.copy()
            if force_spill[b]:
                m[p] = False
            idx = np.flatnonzero(m)
            if idx.size == 0:
                return -1
            ordered = idx[np.lexsort((idx, score[idx]))]
            if not force_spill[b] and mask[p] and score[p] <= score[ordered[0]]:
                return p
            kk = min(k, ordered.size)
            if rng is None:
                return int(ordered[0])
            return int(ordered[rng.integers(0, kk)])

        wants_accel = np.any(d[ACCEL_COLUMNS,] > 0)
        accel_free = np.all(totals[:, ACCEL_COLUMNS,] <= 0, axis=1)
        node, granted = -1, False
        if config.avoid_accel_nodes and not wants_accel:
            c = pick(availm & accel_free)
            if c >= 0:
                node, granted = c, True
        if node < 0:
            c = pick(availm)
            if c >= 0:
                node, granted = c, True
            elif not config.require_available:
                c = pick(feas & ~availm)
                if c >= 0:
                    node, granted = c, False
        if granted:
            avail[node] -= d
        out_nodes.append(node)
        out_granted.append(granted)
    return np.array(out_nodes), np.array(out_granted), avail
