"""Node-label selector matching, shared by both runtimes.

Analog of the reference's label-selector semantics
(/root/reference/src/ray/common/scheduling/label_selector.h,
node_label_scheduling_policy.cc): a selector value may be a string
(equality), a list/tuple/set (in), or None (key exists). ICI-slice
affinity is expressed as labels (e.g. {"slice": "s0"}, util/tpu.py:226-265).
"""
from __future__ import annotations

from typing import Dict, Optional


def match_labels(labels: Dict[str, str], selector: Optional[dict]) -> bool:
    for k, v in (selector or {}).items():
        if v is None:
            if k not in labels:
                return False
        elif isinstance(v, (list, tuple, set)):
            if labels.get(k) not in {str(x) for x in v}:
                return False
        elif labels.get(k) != str(v):
            return False
    return True
