"""Resource model: string-interned vocabulary + dense tensor packing.

TPU-first redesign of the reference's resource model
(/root/reference/src/ray/common/scheduling/cluster_resource_data.h:39,308 and
scheduling_ids.h:45). Instead of per-node hash maps of FixedPoint scalars, the
cluster view is a pair of dense ``float32 [num_nodes, num_resources]`` arrays
(totals / available) so that every scheduling decision can be a batched XLA
program. String resource names are interned to dense column ids at the edge
only (like StringIdMap), and the *authoritative* bookkeeping on grant/return
is exact int64 fixed-point (1e-4 quantum, mirroring fixed_point.h:26) host-side;
the device arrays are the approximate scoring view (eventually-consistent, the
same trust model the reference assigns to ClusterResourceManager).

Unit & exactness contract
-------------------------
Quantities are floats in HUMAN units — CPU/GPU/TPU as device counts,
``memory`` / ``object_store_memory`` in whatever unit the caller adopts
(counts, GiB, or bytes), custom resources likewise. Two layers, two
guarantees:

- **Admission is exact.** Every quantity is quantized once at the edge to
  int64 fixed point (``to_fp``, 1e-4 quantum like the reference's
  FixedPoint) and all grant/release arithmetic — the agent ledger
  (native/ledger.cc) and the local-runtime ``NodeResourceLedger`` — is
  integer. Bytes-valued resources (e.g. ``memory: 2**30``) admit exactly:
  int64 fixed point is exact through 2**59, so sums/compares never drift
  and the last byte is grantable (tests/test_resource_units.py proves the
  boundary).
- **Scoring is float32 and approximate past ``MAX_EXACT_VIEW_TOTAL``.**
  The dense view arrays feed the batched XLA kernels; float32 represents
  the 1e-4 quantum exactly only while value/1e-4 fits the 24-bit
  mantissa, i.e. magnitudes ≤ 2**24 × 1e-4 ≈ 1677.72. Larger totals
  (bytes-valued memory) degrade only *scoring/feasibility pre-checks*
  (float32 spacing at 2**30 is 128) — a stale-view over-grant is caught by
  the agents' exact grant-or-reject and re-queued, the same trust model
  the reference assigns its eventually-consistent
  ClusterResourceManager. ``ClusterView.add_node`` warns once per
  resource name when a total crosses the bound so the precision trade is
  loud, not silent.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np

# Fixed-point quantum: 1/10000, like the reference FixedPoint
# (/root/reference/src/ray/common/scheduling/fixed_point.h:26).
FP_SCALE = 10_000

# Predefined resource columns. The reference's predefined set is
# CPU/MEM/GPU/OBJECT_STORE_MEM (cluster_resource_data.h); we add TPU as a
# first-class accelerator column.
CPU = 0
MEMORY = 1
OBJECT_STORE_MEMORY = 2
GPU = 3
TPU = 4
NUM_PREDEFINED = 5

PREDEFINED_NAMES = ("CPU", "memory", "object_store_memory", "GPU", "TPU")

# Columns used by CalculateCriticalResourceUtilization
# (cluster_resource_data.cc:62-77): CPU, MEM, OBJECT_STORE_MEM.
CRITICAL_COLUMNS = (CPU, MEMORY, OBJECT_STORE_MEMORY)


# Above this magnitude the float32 VIEW can no longer represent the 1e-4
# quantum exactly: exactness needs value/1e-4 ≤ 2^24 (float32's 24-bit
# mantissa), i.e. value ≤ 1677.7216. Admission stays exact (int64
# ledger) at any magnitude; scoring becomes approximate past this.
MAX_EXACT_VIEW_TOTAL = float(1 << 24) / FP_SCALE

_warned_view_precision: set = set()


def _warn_view_precision(name: str, value: float) -> None:
    if name in _warned_view_precision:
        return
    _warned_view_precision.add(name)
    import logging

    logging.getLogger("ray_tpu.scheduler").warning(
        "resource %r total %.4g exceeds MAX_EXACT_VIEW_TOTAL (%.4g): the "
        "float32 scoring view is approximate at this magnitude (admission "
        "stays exact via the int64 ledger). Consider coarser units (GiB "
        "instead of bytes) for exact scoring.",
        name,
        value,
        MAX_EXACT_VIEW_TOTAL,
    )


def to_fp(value: float) -> int:
    """Quantize a python float to exact int64 fixed point (round-to-nearest)."""
    return int(round(float(value) * FP_SCALE))


def from_fp(value: int) -> float:
    return value / FP_SCALE


class ResourceVocab:
    """Interns resource names to dense column indices.

    Thread-safe, append-only. Column layout: predefined columns first, then
    custom resources in interning order. ``capacity`` fixes the dense width so
    jitted kernels see a static resource axis; growing past capacity doubles
    it (a recompile boundary, expected to be rare — the reference similarly
    treats the resource universe as small and slowly-growing).
    """

    def __init__(self, capacity: int = 16):
        assert capacity >= NUM_PREDEFINED
        self._lock = threading.Lock()
        self._name_to_col: Dict[str, int] = {
            name: i for i, name in enumerate(PREDEFINED_NAMES)
        }
        self._names: List[str] = list(PREDEFINED_NAMES)
        self.capacity = capacity

    def intern(self, name: str) -> int:
        with self._lock:
            col = self._name_to_col.get(name)
            if col is None:
                col = len(self._names)
                self._names.append(name)
                self._name_to_col[name] = col
                while col >= self.capacity:
                    self.capacity *= 2
            return col

    def get(self, name: str) -> Optional[int]:
        return self._name_to_col.get(name)

    def name(self, col: int) -> str:
        return self._names[col]

    @property
    def num_resources(self) -> int:
        return len(self._names)

    def pack(self, resource_map: Mapping[str, float]) -> np.ndarray:
        """Pack a {name: quantity} map into a dense float32 row [capacity]."""
        row = np.zeros(self.capacity, dtype=np.float32)
        for name, qty in resource_map.items():
            row[self.intern(name)] = float(qty)
        return row

    def pack_fp(self, resource_map: Mapping[str, float]) -> Dict[int, int]:
        """Exact fixed-point form: {column: int64 quantity}, zeros dropped."""
        out: Dict[int, int] = {}
        for name, qty in resource_map.items():
            v = to_fp(qty)
            if v != 0:
                out[self.intern(name)] = v
        return out

    def unpack(self, row: np.ndarray) -> Dict[str, float]:
        return {
            self._names[i]: float(row[i])
            for i in range(min(len(self._names), len(row)))
            if row[i] != 0
        }


@dataclass
class ResourceRequest:
    """A task/bundle resource demand (reference: ResourceRequest,
    cluster_resource_data.h:39). Exact fixed-point host form."""

    demands: Dict[int, int] = field(default_factory=dict)  # col -> fp qty

    @classmethod
    def from_map(cls, vocab: ResourceVocab, m: Mapping[str, float]) -> "ResourceRequest":
        return cls(vocab.pack_fp(m))

    def is_empty(self) -> bool:
        return not self.demands

    def dense(self, width: int) -> np.ndarray:
        # memoized per width: schedulers re-densify the same parked request
        # every retry round under contention (requests are immutable)
        cache = getattr(self, "_dense_cache", None)
        if cache is not None and cache[0] == width:
            return cache[1]
        row = np.zeros(width, dtype=np.float32)
        for col, fp in self.demands.items():
            row[col] = from_fp(fp)
        row.flags.writeable = False  # shared: accidental mutation raises
        object.__setattr__(self, "_dense_cache", (width, row))
        return row

    def has(self, col: int) -> bool:
        return self.demands.get(col, 0) > 0


class NodeResourceLedger:
    """Authoritative per-node resource accounting in exact fixed point.

    This is the grant-time admission check — the analog of the reference's
    LocalResourceManager (local_resource_manager.h:58): the dense device view
    may be stale, but a grant only succeeds if this ledger says so
    (grant-or-reject under eventually-consistent views,
    local_lease_manager.h:39-61).
    """

    def __init__(self, vocab: ResourceVocab, total: Mapping[str, float]):
        self.vocab = vocab
        self._lock = threading.Lock()
        self.total_fp: Dict[int, int] = vocab.pack_fp(total)
        self.avail_fp: Dict[int, int] = dict(self.total_fp)

    def is_feasible(self, req: ResourceRequest) -> bool:
        with self._lock:
            return all(self.total_fp.get(c, 0) >= q for c, q in req.demands.items())

    def is_available(self, req: ResourceRequest) -> bool:
        with self._lock:
            return all(self.avail_fp.get(c, 0) >= q for c, q in req.demands.items())

    def try_allocate(self, req: ResourceRequest) -> bool:
        with self._lock:
            if any(
                self.avail_fp.get(c, 0) < q for c, q in req.demands.items()
            ):
                return False
            for c, q in req.demands.items():
                self.avail_fp[c] = self.avail_fp.get(c, 0) - q
            return True

    def release(self, req: ResourceRequest) -> None:
        with self._lock:
            for c, q in req.demands.items():
                self.avail_fp[c] = self.avail_fp.get(c, 0) + q
                # Floating credit is a bug; exact arithmetic makes this checkable.
                assert self.avail_fp[c] <= self.total_fp.get(c, 0) + 0, (
                    f"over-release of resource {self.vocab.name(c)}"
                )

    def add_capacity(self, extra: Mapping[str, float]) -> None:
        with self._lock:
            for c, q in self.vocab.pack_fp(extra).items():
                self.total_fp[c] = self.total_fp.get(c, 0) + q
                self.avail_fp[c] = self.avail_fp.get(c, 0) + q

    def total_map(self) -> Dict[str, float]:
        with self._lock:
            return {
                self.vocab.name(c): from_fp(q) for c, q in self.total_fp.items() if q
            }

    def avail_map(self) -> Dict[str, float]:
        with self._lock:
            return {
                self.vocab.name(c): from_fp(q) for c, q in self.avail_fp.items() if q
            }


def make_ledger(vocab: ResourceVocab, total: Mapping[str, float]):
    """Prefer the native C++ ledger (ray_tpu/native/ledger.cc — the
    LocalResourceManager-analog admission hot path); fall back to the pure
    Python implementation when the toolchain is unavailable.
    Disable with RAY_TPU_NATIVE_LEDGER=0."""
    from ray_tpu.config import cfg

    if cfg.native_ledger:
        try:
            from ray_tpu.native.native_ledger import NativeNodeResourceLedger

            return NativeNodeResourceLedger(vocab, total)
        except Exception:  # noqa: BLE001 - no compiler / build failure
            pass
    return NodeResourceLedger(vocab, total)


class ClusterView:
    """Dense cluster resource view: the scheduler dataplane.

    The analog of ClusterResourceManager (cluster_resource_manager.h) — every
    node's totals/availables as rows of dense arrays, fed by the resource
    gossip (§ray_syncer). Kernels consume ``totals``/``avail`` as float32
    device arrays; this class owns the host mirrors and the node-id interning.
    """

    #: label key the head's node registration carries a node type under
    #: (autoscaler.NODE_TYPE_LABEL) — ``add_node`` interns it automatically
    NODE_TYPE_LABEL = "ray_tpu.io/node-type"

    def __init__(self, vocab: ResourceVocab, capacity_nodes: int = 8):
        self.vocab = vocab
        self.capacity_nodes = capacity_nodes
        self._node_ids: List[str] = []
        self._id_to_row: Dict[str, int] = {}
        self.totals = np.zeros((capacity_nodes, vocab.capacity), dtype=np.float32)
        self.avail = np.zeros((capacity_nodes, vocab.capacity), dtype=np.float32)
        self.alive = np.zeros(capacity_nodes, dtype=bool)
        self.labels: List[Dict[str, str]] = [dict() for _ in range(capacity_nodes)]
        # --- heterogeneity (Gavel-style throughput matrix, factorized) ---
        # node_types[row] = interned node-type id; type_throughput[t, c] =
        # relative throughput of resource column c on type t (1.0 =
        # baseline). The kernels derive a per-(shape, node-type) effective
        # throughput from these (hybrid._het_penalty) — the resident
        # encoding of Gavel's throughput matrix for an open-ended shape
        # universe. Type 0 ("default") is the all-ones baseline.
        self.node_types = np.zeros(capacity_nodes, dtype=np.int32)
        self.type_names: List[str] = ["default"]
        self._type_to_id: Dict[str, int] = {"default": 0}
        self.type_throughput = np.ones(
            (4, vocab.capacity), dtype=np.float32
        )
        # Device-mirror bookkeeping (DeviceSchedulerState): topo_version bumps
        # on any change that needs a full re-upload (membership, array
        # reshapes, totals edits); dirty_rows are availability rows whose
        # host value changed since the last device sync.
        self.topo_version = 0
        self.dirty_rows: set = set()
        # Monotone counter over ALL mutations — schedulers use it to retry
        # parked-infeasible work only when the cluster actually changed.
        self.change_counter = 0

    @property
    def num_nodes(self) -> int:
        return len(self._node_ids)

    def _grow(self, min_nodes: int, min_res: int) -> None:
        n_cap, r_cap = self.totals.shape
        new_n = n_cap
        while new_n < min_nodes:
            new_n *= 2
        new_r = r_cap
        while new_r < min_res:
            new_r *= 2
        if (new_n, new_r) != (n_cap, r_cap):
            for attr in ("totals", "avail"):
                old = getattr(self, attr)
                new = np.zeros((new_n, new_r), dtype=np.float32)
                new[:n_cap, :r_cap] = old
                setattr(self, attr, new)
            self.alive = np.resize(self.alive, new_n)
            self.alive[n_cap:] = False
            self.node_types = np.resize(self.node_types, new_n)
            self.node_types[n_cap:] = 0
            if new_r != r_cap:
                thr = np.ones(
                    (self.type_throughput.shape[0], new_r), dtype=np.float32
                )
                thr[:, :r_cap] = self.type_throughput
                self.type_throughput = thr
            self.labels.extend(dict() for _ in range(new_n - n_cap))
            self.capacity_nodes = new_n

    def register_node_type(
        self,
        name: str,
        throughput: Optional[Mapping[str, float]] = None,
    ) -> int:
        """Intern a node type and (optionally) its per-resource relative
        throughput factors ({resource name: factor}, 1.0 = baseline,
        unnamed columns default to 1.0). Re-registering updates the
        factors. Any change bumps ``topo_version`` — the resident
        throughput matrix full-syncs with the next round."""
        tid = self._type_to_id.get(name)
        if tid is None:
            tid = len(self.type_names)
            self.type_names.append(name)
            self._type_to_id[name] = tid
            if tid >= self.type_throughput.shape[0]:
                thr = np.ones(
                    (self.type_throughput.shape[0] * 2,
                     self.type_throughput.shape[1]),
                    dtype=np.float32,
                )
                thr[: self.type_throughput.shape[0]] = self.type_throughput
                self.type_throughput = thr
        if throughput:
            cols = {self.vocab.intern(n): float(v) for n, v in throughput.items()}
            if self.vocab.capacity > self.type_throughput.shape[1]:
                self._grow(max(self.num_nodes, 1), self.vocab.capacity)
            row = np.ones(self.type_throughput.shape[1], dtype=np.float32)
            for col, factor in cols.items():
                row[col] = factor
            self.type_throughput[tid] = row
        self.topo_version += 1
        self.change_counter += 1
        return tid

    def add_node(
        self,
        node_id: str,
        total: Mapping[str, float],
        labels: Optional[Mapping[str, str]] = None,
        node_type: Optional[str] = None,
    ) -> int:
        for name, v in total.items():
            if float(v) > MAX_EXACT_VIEW_TOTAL:
                _warn_view_precision(name, float(v))
        row_total = self.vocab.pack(total)
        self._grow(len(self._node_ids) + 1, self.vocab.capacity)
        if row_total.shape[0] < self.totals.shape[1]:
            row_total = np.resize(row_total, self.totals.shape[1])
        row = self._id_to_row.get(node_id)
        if row is None:
            row = len(self._node_ids)
            self._node_ids.append(node_id)
            self._id_to_row[node_id] = row
        self.totals[row, : len(row_total)] = row_total
        self.avail[row, : len(row_total)] = row_total
        self.alive[row] = True
        self.labels[row] = dict(labels or {})
        if node_type is None and labels:
            node_type = labels.get(self.NODE_TYPE_LABEL)
        self.node_types[row] = (
            self.register_node_type(node_type) if node_type else 0
        )
        self.topo_version += 1
        self.change_counter += 1
        return row

    def remove_node(self, node_id: str) -> None:
        row = self._id_to_row.get(node_id)
        if row is not None:
            self.alive[row] = False
            self.totals[row] = 0
            self.avail[row] = 0
            self.topo_version += 1
            self.change_counter += 1

    def row_of(self, node_id: str) -> int:
        return self._id_to_row[node_id]

    def row_if_known(self, node_id: str) -> Optional[int]:
        """Row index, or None for a node this view never interned —
        locality scoring must skip stale directory locations instead of
        raising (the object outlives its node's membership)."""
        return self._id_to_row.get(node_id)

    def node_id(self, row: int) -> str:
        return self._node_ids[row]

    def update_available(self, node_id: str, avail: Mapping[str, float]) -> None:
        """Apply a gossip snapshot (RaySyncer RESOURCE_VIEW analog)."""
        row = self._id_to_row[node_id]
        packed = self.vocab.pack(avail)
        if packed.shape[0] > self.avail.shape[1]:
            self._grow(self.num_nodes, packed.shape[0])
            self.topo_version += 1
        self.avail[row, : len(packed)] = packed
        self.dirty_rows.add(row)
        self.change_counter += 1

    def subtract(self, row: int, demand: np.ndarray) -> None:
        self.avail[row, : len(demand)] -= demand
        self.dirty_rows.add(row)
        self.change_counter += 1

    def subtract_many(self, rows: np.ndarray, demands: np.ndarray) -> None:
        """Vectorized grant deduction: one duplicate-safe scatter-add for a
        whole round's placements instead of a per-spec Python call (the
        per-grant loop was the dominant host cost of a 4k-lease round at
        10k nodes). ``rows`` int[B], ``demands`` f32[B,<=R]."""
        if rows.size == 0:
            return
        np.subtract.at(
            self.avail[:, : demands.shape[1]],
            rows,
            demands,
        )
        self.dirty_rows.update(int(r) for r in np.unique(rows))
        self.change_counter += 1

    def add(self, row: int, demand: np.ndarray) -> None:
        self.avail[row, : len(demand)] += demand
        self.dirty_rows.add(row)
        self.change_counter += 1

    def active_arrays(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(totals, avail, alive) trimmed to the populated node rows."""
        n = self.num_nodes
        return self.totals[:n], self.avail[:n], self.alive[:n]

    def active_type_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """(node_types int32[N], type_throughput f32[T,R]) trimmed to the
        populated node rows / registered types — the heterogeneity inputs
        the device mirror keeps resident (full-synced on topo_version
        moves, which every type registration bumps)."""
        n = self.num_nodes
        t = len(self.type_names)
        return (
            self.node_types[:n],
            self.type_throughput[:t, : self.totals.shape[1]],
        )
