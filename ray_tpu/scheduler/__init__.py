"""TPU-batched cluster scheduler: the north-star subsystem.

The reference schedules one lease at a time with hash-map scans
(/root/reference/src/ray/raylet/scheduling/). Here, cluster state is dense
device arrays and every policy is a compiled, batched XLA program:

- resources.py — vocabulary interning, exact fixed-point ledger (authoritative
  grants), dense ClusterView (approximate scoring view).
- hybrid.py    — batched HybridSchedulingPolicy (fidelity + throughput modes).
- bundles.py   — placement-group PACK/SPREAD/STRICT_* bin-packing kernels.
- binpack.py   — autoscaler first-fit residual + node-type utilization scorer.
"""
from .resources import (  # noqa: F401
    CPU,
    GPU,
    MEMORY,
    OBJECT_STORE_MEMORY,
    TPU,
    ClusterView,
    NodeResourceLedger,
    ResourceRequest,
    ResourceVocab,
)
from .hybrid import (  # noqa: F401
    HybridConfig,
    ScoreWeights,
    hybrid_schedule_batch,
    hybrid_schedule_reference,
    hybrid_schedule_rounds,
    hybrid_schedule_shapes_multi,
)
from .bundles import schedule_bundles, sort_bundles  # noqa: F401
from .binpack import (  # noqa: F401
    DeltaBinPacker,
    bin_pack_residual,
    pick_best_node_type,
    solve_pack_counts,
    sort_demands,
    utilization_scores,
)
from .pipeline import SchedulerPipeline  # noqa: F401
from .elasticity import (  # noqa: F401
    DemandMatrix,
    ElasticPlan,
    ElasticSnapshot,
    ElasticityController,
    GangWant,
    assemble_demand,
    build_plan,
    credit_gang_usage,
    dedupe_task_shapes,
    solve_demand,
)
