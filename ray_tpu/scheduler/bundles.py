"""Placement-group bundle bin-packing as JAX kernels.

Reimplements the semantics of the reference's bundle scheduling policies
(/root/reference/src/ray/raylet/scheduling/policy/bundle_scheduling_policy.cc:156-383
and scorer.cc:20-46) as compiled XLA programs over dense
``[nodes, resources]`` / ``[bundles, resources]`` arrays:

- PACK     — best node for the highest-priority unplaced bundle, then fill
             that node with every remaining bundle that fits, retire the node,
             repeat (bundle_scheduling_policy.cc:156-235).
- SPREAD   — each bundle prefers a not-yet-used candidate node, falling back
             to already-selected nodes (:238-301).
- STRICT_PACK — aggregate all bundles into one request, one best node (:304).
- STRICT_SPREAD — every bundle on a distinct node.

Scoring is LeastResourceScorer (scorer.cc:20-46): over the *requested*
resources, sum of (available - requested) / available (0 when available is
0), -1 when the node can't host the bundle; higher is better; ties go to the
lowest node row (the reference iterates an unordered hash map — we pin the
deterministic choice, which is what its unit tests do too).

Bundle priority order (SortRequiredResources, :61-129): GPU desc, then each
custom resource column desc, then object-store-memory, memory, CPU desc.
Sorting happens host-side (`sort_bundles`) — bundle lists are small; the
packing itself is the device program.
"""
from __future__ import annotations

import functools
import time as _time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .resources import CPU, GPU, MEMORY, NUM_PREDEFINED, OBJECT_STORE_MEMORY

_EPS = 1e-5


def sort_bundles(bundles: np.ndarray) -> np.ndarray:
    """Return bundle indices in scheduling priority order (host-side)."""
    b, r = bundles.shape
    # np.lexsort: last key is primary. Priority: GPU, customs (in column
    # order), OBJ, MEM, CPU — all descending; final tie-break: original index.
    keys = [np.arange(b)]  # least significant: stable original order
    for col in (CPU, MEMORY, OBJECT_STORE_MEMORY):
        keys.append(-bundles[:, col])
    for col in range(r - 1, NUM_PREDEFINED - 1, -1):
        keys.append(-bundles[:, col])
    keys.append(-bundles[:, GPU])
    return np.lexsort(tuple(keys))


def _least_resource_score(avail_rows: jax.Array, demand: jax.Array) -> jax.Array:
    """LeastResourceScorer over all node rows: f32[N], -1 = can't host."""
    requested = demand > 0
    ok = jnp.all(avail_rows >= demand[None, :] - _EPS, axis=1)
    safe = jnp.where(avail_rows > 0, avail_rows, 1.0)
    terms = jnp.where(
        requested[None, :] & (avail_rows > 0),
        (avail_rows - demand[None, :]) / safe,
        0.0,
    )
    score = jnp.sum(terms, axis=1)
    return jnp.where(ok, score, -1.0)


class PackResult(NamedTuple):
    node: jax.Array      # int32[B] node row per bundle (sorted order), -1 on fail
    success: jax.Array   # bool scalar — all bundles placed
    avail_out: jax.Array  # f32[N,R] availability after placement (valid iff success)


@jax.jit
def pack_bundles(
    totals: jax.Array,
    avail: jax.Array,
    alive: jax.Array,
    bundles: jax.Array,  # f32[B,R] already in priority order
) -> PackResult:
    """PACK strategy. ``bundles`` must already be priority-sorted."""
    n = totals.shape[0]
    b = bundles.shape[0]

    def outer(i, state):
        placed, cand, avail_run, failed = state
        unplaced = placed < 0
        any_un = jnp.any(unplaced)
        j = jnp.argmax(unplaced)  # first unplaced (priority order)
        d = bundles[j]
        score = _least_resource_score(avail_run, d)
        score = jnp.where(cand & alive, score, -jnp.inf)
        best = jnp.argmax(score)  # first max → lowest row on ties
        ok = (score[best] >= 0) & any_un & ~failed

        # Fill `best` with every unplaced bundle that fits, in priority order.
        def fill(carry, idx):
            node_avail, placed = carry
            d2 = bundles[idx]
            can = (
                ok
                & (placed[idx] < 0)
                & jnp.all(node_avail >= d2 - _EPS)
            )
            node_avail = jnp.where(can, node_avail - d2, node_avail)
            placed = placed.at[idx].set(
                jnp.where(can, best.astype(jnp.int32), placed[idx])
            )
            return (node_avail, placed), None

        (node_avail, placed), _ = jax.lax.scan(
            fill, (avail_run[best], placed), jnp.arange(b)
        )
        avail_run = jnp.where(ok, avail_run.at[best].set(node_avail), avail_run)
        cand = cand.at[best].set(jnp.where(ok, False, cand[best]))
        failed = failed | (any_un & (score[best] < 0))
        return placed, cand, avail_run, failed

    placed0 = jnp.full((b,), -1, dtype=jnp.int32)
    placed, _, avail_out, failed = jax.lax.fori_loop(
        0, min(b, n), outer, (placed0, alive, avail, jnp.bool_(False))
    )
    success = jnp.all(placed >= 0) & ~failed
    return PackResult(placed, success, avail_out)


@functools.partial(jax.jit, static_argnames=("strict",))
def spread_bundles(
    totals: jax.Array,
    avail: jax.Array,
    alive: jax.Array,
    bundles: jax.Array,  # f32[B,R] priority-sorted
    *,
    strict: bool = False,
) -> PackResult:
    """SPREAD / STRICT_SPREAD strategies."""

    def step(state, d):
        fresh, avail_run = state  # fresh: bool[N] not-yet-selected candidates
        score = _least_resource_score(avail_run, d)
        s1 = jnp.where(fresh & alive, score, -jnp.inf)
        best1 = jnp.argmax(s1)
        ok1 = s1[best1] >= 0
        if strict:
            best, ok = best1, ok1
        else:
            s2 = jnp.where(~fresh & alive, score, -jnp.inf)
            best2 = jnp.argmax(s2)
            ok2 = s2[best2] >= 0
            best = jnp.where(ok1, best1, best2)
            ok = ok1 | ok2
        avail_run = jnp.where(ok, avail_run.at[best].add(-d), avail_run)
        fresh = fresh.at[best].set(jnp.where(ok, False, fresh[best]))
        node = jnp.where(ok, best.astype(jnp.int32), -1)
        return (fresh, avail_run), node

    (_, avail_out), nodes = jax.lax.scan(step, (alive, avail), bundles)
    success = jnp.all(nodes >= 0)
    return PackResult(nodes, success, avail_out)


@jax.jit
def strict_pack_bundles(
    totals: jax.Array,
    avail: jax.Array,
    alive: jax.Array,
    bundles: jax.Array,
) -> PackResult:
    """STRICT_PACK: all bundles on one node (aggregate demand)."""
    agg = jnp.sum(bundles, axis=0)
    score = _least_resource_score(avail, agg)
    score = jnp.where(alive, score, -jnp.inf)
    best = jnp.argmax(score)
    ok = score[best] >= 0
    b = bundles.shape[0]
    nodes = jnp.where(ok, jnp.full((b,), best, dtype=jnp.int32), -1)
    avail_out = jnp.where(ok, avail.at[best].add(-agg), avail)
    return PackResult(nodes, ok, avail_out)


def rows_to_avoid_mask(rows, alive) -> "np.ndarray | None":
    """Bool mask over the node axis from a list of row indices (None /
    out-of-range entries dropped — callers resolve rows from node ids
    against a snapshot that may be narrower than the live view). Returns
    None when nothing survives, so callers can skip the masked pass."""
    width = int(np.shape(alive)[0])
    rows = [
        int(r) for r in rows if r is not None and 0 <= int(r) < width
    ]
    if not rows:
        return None
    mask = np.zeros(width, dtype=bool)
    mask[np.asarray(rows)] = True
    return mask


def schedule_bundles_soft_avoid(
    totals, avail, alive, bundles, strategy, avoid_rows
):
    """SOFT anti-affinity placement (gang-aware reshape): first run the
    kernels with ``avoid_rows`` masked dead, then fall back to the full
    cluster when the masked placement is infeasible — a gang avoiding a
    flapping node must never park behind the preference. The one home
    of this policy; both the head PG path and the local runtime call
    it."""
    avoid = rows_to_avoid_mask(avoid_rows, alive)
    if avoid is not None:
        rows, success, left = schedule_bundles(
            totals, avail, alive, bundles, strategy, avoid=avoid
        )
        if success:
            return rows, success, left
    return schedule_bundles(totals, avail, alive, bundles, strategy)


def schedule_bundles(
    totals,
    avail,
    alive,
    bundles: np.ndarray,
    strategy: str = "PACK",
    avoid: "np.ndarray | None" = None,
):
    """Host entry point: sort, pad, dispatch to the strategy kernel, unsort.

    Returns (node_per_bundle int32[B] in *original* bundle order, success,
    avail_out). Mirrors ClusterResourceScheduler::Schedule
    (cluster_resource_scheduler.cc:397) + SortSchedulingResult.

    Compile caching: the bundle axis is padded to the next power of two
    with zero-demand rows, so PG churn across varying bundle counts hits
    a handful of cached XLA executables instead of re-tracing each
    distinct B (a ~100ms trace per new shape — the dominant cost of a
    create/remove pair before jit warms). Pads sort last (zero demand),
    place for free on any alive node, and consume nothing; success is
    computed over the real rows only, so a STRICT_SPREAD short on nodes
    for its PADS (but not its real bundles) still succeeds.
    """
    bundles = np.asarray(bundles, dtype=np.float32)
    b = bundles.shape[0]
    if b == 0:
        return np.zeros(0, dtype=np.int32), True, avail
    t0 = _time.time()
    t0_mono = _time.perf_counter()
    if avoid is not None:
        # anti-affinity mask (gang-aware reshape placement): avoided rows
        # enter the kernels as dead — they score -inf/-1 and can never
        # host a bundle. Callers wanting SOFT avoidance re-run without
        # the mask on failure; the kernels themselves stay oblivious.
        alive = jnp.logical_and(
            jnp.asarray(alive, dtype=bool),
            jnp.logical_not(jnp.asarray(avoid, dtype=bool)),
        )
    order = sort_bundles(bundles)
    sorted_host = bundles[order]
    padded = 1 << max(0, (b - 1).bit_length())
    if padded > b:
        sorted_host = np.concatenate(
            [
                sorted_host,
                np.zeros((padded - b, bundles.shape[1]), dtype=np.float32),
            ]
        )
    sorted_bundles = jnp.asarray(sorted_host)
    if strategy == "PACK":
        res = pack_bundles(totals, avail, alive, sorted_bundles)
    elif strategy == "SPREAD":
        res = spread_bundles(totals, avail, alive, sorted_bundles, strict=False)
    elif strategy == "STRICT_SPREAD":
        res = spread_bundles(totals, avail, alive, sorted_bundles, strict=True)
    elif strategy == "STRICT_PACK":
        res = strict_pack_bundles(totals, avail, alive, sorted_bundles)
    else:
        raise ValueError(f"unknown placement strategy: {strategy}")
    nodes_sorted = np.asarray(res.node)[:b]
    nodes = np.full_like(nodes_sorted, -1)
    nodes[order] = nodes_sorted
    success = bool((nodes_sorted >= 0).all())
    # PG rounds are rare (create/reshape), so a span per call is cheap;
    # it lands beside the sched_round slices in the trace export
    try:
        from ray_tpu.util.tracing import SPANS

        SPANS.record(
            "pg_schedule",
            "scheduler",
            t0,
            _time.perf_counter() - t0_mono,
            pid="scheduler",
            strategy=strategy,
            bundles=int(b),
            success=success,
        )
    except Exception:  # noqa: BLE001 - observability only
        pass
    return nodes, success, res.avail_out
