"""Model zoo: TPU-first model implementations (pure-JAX pytree functions)."""
