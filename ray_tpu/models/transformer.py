"""Flagship model: LLaMA-style decoder, TPU-first.

Design (vs the reference, which wraps vLLM/torch and has no native model):

- Pure-functional pytree params; layer weights stacked on a leading axis so
  the forward is a ``lax.scan`` over layers (one compile of one block).
- bfloat16 compute, fp32 RMSNorm/softmax accumulators (MXU-friendly).
- 4D parallelism on the canonical mesh (parallel/mesh.py):
  * dp — batch sharding (gradient psum inserted by XLA),
  * tp — Megatron-style head/hidden sharding via parameter PartitionSpecs,
  * pp — GPipe microbatching over ppermute (ops/pipeline.py),
  * sp — ring attention over ppermute (ops/ring_attention.py),
  * ep — MoE experts sharded over the tp axis (models/moe.py).
- Under jit the whole train step is one XLA program; pp/sp sections run
  manual (shard_map axis_names={'pp','sp'}), dp/tp stay auto.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops.layers import (
    apply_rope,
    attention_reference,
    rms_norm,
    rope_freqs,
    swiglu,
)
from ray_tpu.ops.pipeline import pipeline_apply
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.models import moe as moe_mod


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    n_experts: int = 0          # 0 = dense MLP; >0 = Switch-MoE every layer
    expert_capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    # sequence-parallel attention flavor: "ring" (ppermute KV rotation,
    # ops/ring_attention.py) or "ulysses" (all-to-all head/sequence swap,
    # ops/ulysses.py) — both net-new vs the reference (SURVEY §2.3).
    sp_attention: str = "ring"
    # rematerialize each block in the backward pass (jax.checkpoint) —
    # trades ~1/3 extra FLOPs for O(n_layers) less residual HBM. The
    # standard TPU memory lever for deep/long-sequence configs.
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: ModelConfig, key: jax.Array) -> Dict[str, Any]:
    """Stacked-layer parameter pytree."""
    k = jax.random.split(key, 12)
    d, hd = cfg.d_model, cfg.head_dim
    L = cfg.n_layers
    dt = cfg.dtype

    def norm_init(*shape):
        return jnp.ones(shape, dt)

    def dense_init(key, *shape, scale=None):
        fan_in = shape[-2]
        scale = scale or fan_in**-0.5
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    blocks = {
        "ln1": norm_init(L, d),
        "ln2": norm_init(L, d),
        "wq": dense_init(k[0], L, d, cfg.n_heads * hd),
        "wk": dense_init(k[1], L, d, cfg.n_kv_heads * hd),
        "wv": dense_init(k[2], L, d, cfg.n_kv_heads * hd),
        "wo": dense_init(k[3], L, cfg.n_heads * hd, d),
    }
    if cfg.n_experts > 0:
        blocks["moe"] = moe_mod.init_moe(
            cfg.n_experts, d, cfg.d_ff, L, k[4], dt
        )
    else:
        blocks["w_gate"] = dense_init(k[5], L, d, cfg.d_ff)
        blocks["w_up"] = dense_init(k[6], L, d, cfg.d_ff)
        blocks["w_down"] = dense_init(k[7], L, cfg.d_ff, d)
    return {
        "embed": dense_init(k[8], cfg.vocab_size, d, scale=0.02),
        "blocks": blocks,
        "ln_f": norm_init(d),
        "head": dense_init(k[9], d, cfg.vocab_size),
    }


def param_specs(cfg: ModelConfig, pp: int = 1) -> Dict[str, Any]:
    """PartitionSpec tree: Megatron tp sharding; layer axis sharded over pp
    when pipelined (each stage holds its slice of the stack)."""
    lp = "pp" if pp > 1 else None
    blocks = {
        "ln1": P(lp, None),
        "ln2": P(lp, None),
        "wq": P(lp, None, "tp"),
        "wk": P(lp, None, "tp"),
        "wv": P(lp, None, "tp"),
        "wo": P(lp, "tp", None),
    }
    if cfg.n_experts > 0:
        blocks["moe"] = moe_mod.moe_specs(lp)
    else:
        blocks["w_gate"] = P(lp, None, "tp")
        blocks["w_up"] = P(lp, None, "tp")
        blocks["w_down"] = P(lp, "tp", None)
    return {
        "embed": P("tp", None),
        "blocks": blocks,
        "ln_f": P(None),
        "head": P(None, "tp"),
    }


def shard_params(params, cfg: ModelConfig, mesh: Mesh):
    pp = mesh.shape.get("pp", 1)
    specs = param_specs(cfg, pp)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, jnp.ndarray),
    )


def _block(cfg: ModelConfig, p: Dict[str, jax.Array], h: jax.Array,
           angles: jax.Array, *, sp_manual: bool) -> jax.Array:
    """One decoder block. h: [B, T(_local), D]; angles already offset."""
    b, t, d = h.shape
    hd = cfg.head_dim
    x = rms_norm(h, p["ln1"])
    q = (x @ p["wq"]).reshape(b, t, cfg.n_heads, hd)
    k = (x @ p["wk"]).reshape(b, t, cfg.n_kv_heads, hd)
    v = (x @ p["wv"]).reshape(b, t, cfg.n_kv_heads, hd)
    q = apply_rope(q, angles)
    k = apply_rope(k, angles)
    if sp_manual:
        if cfg.sp_attention == "ulysses":
            from ray_tpu.ops.ulysses import ulysses_attention

            attn = ulysses_attention(q, k, v, "sp", causal=True)
        else:
            attn = ring_attention(q, k, v, "sp", causal=True)
    elif jax.default_backend() not in ("cpu",):
        # TPU: pallas flash kernel (falls back internally on ragged shapes)
        from ray_tpu.ops.flash_attention import flash_attention

        attn = flash_attention(q, k, v, causal=True)
    else:
        attn = attention_reference(q, k, v, causal=True)
    h = h + attn.reshape(b, t, -1) @ p["wo"]
    x = rms_norm(h, p["ln2"])
    if cfg.n_experts > 0:
        y = moe_mod.moe_apply(p["moe"], x, cfg.expert_capacity_factor)
    else:
        y = swiglu(x, p["w_gate"], p["w_up"], p["w_down"])
    return h + y


def _scan_blocks(cfg: ModelConfig, blocks, h, angles, *, sp_manual: bool):
    def body(h, layer_p):
        return _block(cfg, layer_p, h, angles, sp_manual=sp_manual), None

    if cfg.remat:
        # prevent_cse=False: under lax.scan the CSE-prevention barriers
        # are redundant and only cost compile/runtime (jax.checkpoint doc)
        body = jax.checkpoint(body, prevent_cse=False)
    h, _ = jax.lax.scan(body, h, blocks)
    return h


def forward(
    params,
    tokens: jax.Array,  # int32 [B, T]
    cfg: ModelConfig,
    mesh: Optional[Mesh] = None,
    *,
    num_microbatches: int = 0,
) -> jax.Array:
    """Logits [B, T, V]. Dispatches to plain / ring-SP / pipelined paths
    based on the mesh shape (pp/sp manual, dp/tp auto)."""
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    b, t = tokens.shape
    h = params["embed"][tokens].astype(cfg.dtype)
    angles_full = rope_freqs(cfg.head_dim, t, cfg.rope_theta)

    if pp == 1 and sp == 1:
        h = _scan_blocks(cfg, params["blocks"], h, angles_full, sp_manual=False)
    elif pp == 1:
        # sequence-parallel only: ring attention over sp
        def sp_body(blocks, h_loc):
            t_loc = h_loc.shape[1]
            off = jax.lax.axis_index("sp") * t_loc
            ang = jax.lax.dynamic_slice_in_dim(angles_full, off, t_loc)
            return _scan_blocks(cfg, blocks, h_loc, ang, sp_manual=True)

        h = jax.shard_map(
            sp_body,
            mesh=mesh,
            in_specs=(P(), P(None, "sp", None)),
            out_specs=P(None, "sp", None),
            axis_names={"sp"},
            check_vma=True,
        )(params["blocks"], h)
    else:
        # pipeline (optionally + sp): stage-stacked blocks over pp
        m = num_microbatches or max(1, 2 * pp)
        assert b % m == 0, f"batch {b} not divisible by microbatches {m}"
        stages = jax.tree.map(
            lambda x: x.reshape((pp, x.shape[0] // pp) + x.shape[1:]),
            params["blocks"],
        )
        h_mb = h.reshape((m, b // m) + h.shape[1:])

        def pp_body(stage_blocks, x_mb):
            # local view keeps the sharded stage axis as size 1 — drop it
            stage_blocks = jax.tree.map(lambda a: a[0], stage_blocks)
            t_loc = x_mb.shape[2]
            if sp > 1:
                off = jax.lax.axis_index("sp") * t_loc
            else:
                off = 0
            ang = jax.lax.dynamic_slice_in_dim(angles_full, off, t_loc)

            def stage_fn(blocks, x_one):
                return _scan_blocks(
                    cfg, blocks, x_one, ang, sp_manual=sp > 1
                )

            return pipeline_apply(stage_fn, stage_blocks, x_mb, "pp")

        in_layer_spec = P("pp")  # stage axis sharded; rest auto
        h_mb = jax.shard_map(
            pp_body,
            mesh=mesh,
            in_specs=(
                jax.tree.map(lambda _: in_layer_spec, stages),
                P(None, None, "sp", None) if sp > 1 else P(),
            ),
            out_specs=P(None, None, "sp", None) if sp > 1 else P(),
            axis_names={"pp", "sp"},
            check_vma=True,
        )(stages, h_mb)
        h = h_mb.reshape((b,) + h_mb.shape[2:])

    h = rms_norm(h, params["ln_f"])
    return (h @ params["head"]).astype(jnp.float32)


# ---------------------------------------------------------------------------
# KV-cache inference path (prefill + single-token decode), the engine core
# for ray_tpu.llm. Cache layout: {"k","v"}: f32[L, B, S_max, Hkv, Dh].
# ---------------------------------------------------------------------------


def init_kv_cache(cfg: ModelConfig, batch: int, max_len: int):
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _block_with_cache(cfg, p, h, k_cache, v_cache, positions, seq_mask):
    """One block over ``h`` [B, T, D] writing K/V into the cache slice and
    attending over cache[:, :S_max] with a position mask.

    positions: int32[B, T] absolute position of each input token.
    seq_mask: bool[B, S_max] which cache slots are valid *after* this write.
    """
    b, t, d = h.shape
    hd = cfg.head_dim
    x = rms_norm(h, p["ln1"])
    q = (x @ p["wq"]).reshape(b, t, cfg.n_heads, hd)
    k = (x @ p["wk"]).reshape(b, t, cfg.n_kv_heads, hd)
    v = (x @ p["wv"]).reshape(b, t, cfg.n_kv_heads, hd)
    angles = rope_freqs(hd, cfg.max_seq_len, cfg.rope_theta)  # [S,D/2]
    ang = angles[positions]  # [B, T, D/2]
    q = _apply_rope_positions(q, ang)
    k = _apply_rope_positions(k, ang)
    # scatter k/v into the cache at each token's position
    bidx = jnp.arange(b)[:, None].repeat(t, 1)
    k_cache = k_cache.at[bidx, positions].set(k)
    v_cache = v_cache.at[bidx, positions].set(v)
    # attention: q attends to all cached positions <= its own
    s_max = k_cache.shape[1]
    groups = cfg.n_heads // cfg.n_kv_heads
    qh = q.reshape(b, t, cfg.n_kv_heads, groups, hd)
    scores = jnp.einsum(
        "bthgd,bshd->bhgts", qh.astype(jnp.float32),
        k_cache.astype(jnp.float32),
    ) / jnp.sqrt(hd)
    k_pos = jnp.arange(s_max)
    causal = k_pos[None, None, :] <= positions[:, :, None]  # [B,T,S]
    valid = causal & seq_mask[:, None, :]
    scores = jnp.where(valid[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum(
        "bhgts,bshd->bthgd", probs, v_cache.astype(jnp.float32)
    ).astype(h.dtype)
    h = h + attn.reshape(b, t, -1) @ p["wo"]
    x = rms_norm(h, p["ln2"])
    if cfg.n_experts > 0:
        y = moe_mod.moe_apply(p["moe"], x, cfg.expert_capacity_factor)
    else:
        y = swiglu(x, p["w_gate"], p["w_up"], p["w_down"])
    return h + y, k_cache, v_cache


def _apply_rope_positions(x, ang):
    """x: [B, T, H, D]; ang: [B, T, D/2]."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x1, x2 = jnp.split(x, 2, axis=-1)
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(dtype)


def forward_with_cache(
    params,
    tokens: jax.Array,      # int32[B, T]
    positions: jax.Array,   # int32[B, T]
    cache,                  # from init_kv_cache
    seq_mask: jax.Array,    # bool[B, S_max] valid slots incl. these tokens
    cfg: ModelConfig,
):
    """Returns (logits[B, T, V], updated cache). Used for both prefill
    (T = prompt length) and decode (T = 1)."""
    h = params["embed"][tokens].astype(cfg.dtype)

    def body(carry, layer):
        h = carry
        p, kc, vc = layer
        h, kc, vc = _block_with_cache(
            cfg, p, h, kc, vc, positions, seq_mask
        )
        return h, (kc, vc)

    h, (k_new, v_new) = jax.lax.scan(
        body, h, (params["blocks"], cache["k"], cache["v"])
    )
    h = rms_norm(h, params["ln_f"])
    logits = (h @ params["head"]).astype(jnp.float32)
    return logits, {"k": k_new, "v": v_new}


def loss_fn(params, tokens, cfg: ModelConfig, mesh=None, *, num_microbatches=0):
    """Causal LM loss: predict tokens[1:] from tokens[:-1]."""
    logits = forward(
        params, tokens[:, :-1], cfg, mesh, num_microbatches=num_microbatches
    )
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def make_train_step(cfg: ModelConfig, optimizer, mesh=None, *, num_microbatches=0):
    """Returns jittable (params, opt_state, tokens) -> (params, opt_state, loss)."""

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, cfg, mesh, num_microbatches=num_microbatches
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return train_step
