"""Switch-style Mixture-of-Experts with expert parallelism over ``tp``.

The reference only forwards ``expert_parallel_size`` to vLLM (SURVEY §2.3).
Here EP is native: expert weight stacks carry a leading E axis sharded over
the mesh ``tp`` axis, and dispatch is the GShard dense-einsum formulation
(one-hot dispatch/combine tensors — static shapes, MXU-friendly; XLA turns
the einsums into an all-to-all across the expert axis). Top-1 routing with
capacity dropping, Switch-Transformer style.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_moe(n_experts: int, d_model: int, d_ff: int, n_layers: int, key, dtype):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    s_in = d_model**-0.5
    s_ff = d_ff**-0.5

    def init(k, *shape, scale):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    return {
        "router": init(k1, n_layers, d_model, n_experts, scale=s_in),
        "w_gate": init(k2, n_layers, n_experts, d_model, d_ff, scale=s_in),
        "w_up": init(k3, n_layers, n_experts, d_model, d_ff, scale=s_in),
        "w_down": init(k4, n_layers, n_experts, d_ff, d_model, scale=s_ff),
    }


def moe_specs(lp):
    """Experts sharded over tp (= the EP axis); router replicated."""
    return {
        "router": P(lp, None, None),
        "w_gate": P(lp, "tp", None, None),
        "w_up": P(lp, "tp", None, None),
        "w_down": P(lp, "tp", None, None),
    }


def moe_apply(p, x: jax.Array, capacity_factor: float = 1.25) -> jax.Array:
    """x: [B, T, D] -> [B, T, D]."""
    b, t, d = x.shape
    n = b * t
    e = p["router"].shape[-1]
    cap = max(1, int(capacity_factor * n / e))
    xf = x.reshape(n, d)

    logits = (xf.astype(jnp.float32) @ p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)          # [N, E]
    gate = jnp.max(probs, axis=-1)                    # [N]
    expert = jnp.argmax(probs, axis=-1)               # [N]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [N, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0   # position within expert
    keep = (pos >= 0) & (pos < cap)
    pos_clipped = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)
    # dispatch[n, e, c] — GShard dense dispatch tensor
    dispatch = (
        onehot * keep
    )[:, :, None] * jax.nn.one_hot(pos_clipped, cap, dtype=jnp.float32)
    combine = dispatch * gate[:, None, None]

    xin = jnp.einsum("nec,nd->ecd", dispatch, xf.astype(jnp.float32)).astype(
        x.dtype
    )
    g = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xin, p["w_gate"]))
    u = jnp.einsum("ecd,edf->ecf", xin, p["w_up"])
    y = jnp.einsum("ecf,efd->ecd", g * u, p["w_down"])
    out = jnp.einsum("nec,ecd->nd", combine, y.astype(jnp.float32))
    return out.astype(x.dtype).reshape(b, t, d)
