"""PPO: env-runner actors + jitted clipped-surrogate learner."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from .cartpole import CartPoleEnv


# -- policy/value MLP (pure-jax pytree) -------------------------------------


def dense_init(k, i, o):
    """Fan-in-scaled dense layer init (shared by PPO/DQN/IMPALA nets)."""
    return {
        "w": jax.random.normal(k, (i, o), jnp.float32) * (i**-0.5),
        "b": jnp.zeros((o,), jnp.float32),
    }


def init_policy(key, obs_size: int, num_actions: int, hidden: int = 64):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    dense = dense_init

    return {
        "torso": [dense(k1, obs_size, hidden), dense(k2, hidden, hidden)],
        "pi": dense(k3, hidden, num_actions),
        "vf": dense(k4, hidden, 1),
    }


def policy_forward(params, obs):
    h = obs
    for layer in params["torso"]:
        h = jnp.tanh(h @ layer["w"] + layer["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


# -- rollout worker ----------------------------------------------------------


@ray_tpu.remote
class EnvRunner:
    """Collects one rollout segment per call under given policy params."""

    def __init__(self, env_factory: Callable, seed: int):
        self.env = env_factory()
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.env.reset(seed=seed)
        self.episode_return = 0.0
        self.completed_returns: List[float] = []

    def rollout(self, params, num_steps: int) -> Dict[str, np.ndarray]:
        obs_buf, act_buf, rew_buf, done_buf, logp_buf, val_buf = (
            [], [], [], [], [], [],
        )
        self.completed_returns = []
        for _ in range(num_steps):
            logits, value = policy_forward(
                params, jnp.asarray(self.obs[None])
            )
            probs = np.asarray(jax.nn.softmax(logits[0]))
            action = int(self.rng.choice(len(probs), p=probs / probs.sum()))
            logp = float(np.log(probs[action] + 1e-9))
            nobs, reward, term, trunc, _ = self.env.step(action)
            obs_buf.append(self.obs)
            act_buf.append(action)
            rew_buf.append(reward)
            done_buf.append(term or trunc)
            logp_buf.append(logp)
            val_buf.append(float(value[0]))
            self.episode_return += reward
            if term or trunc:
                self.completed_returns.append(self.episode_return)
                self.episode_return = 0.0
                self.obs, _ = self.env.reset()
            else:
                self.obs = nobs
        _, last_value = policy_forward(params, jnp.asarray(self.obs[None]))
        return {
            "obs": np.asarray(obs_buf, np.float32),
            "actions": np.asarray(act_buf, np.int32),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, np.bool_),
            "logp": np.asarray(logp_buf, np.float32),
            "values": np.asarray(val_buf, np.float32),
            "last_value": float(last_value[0]),
            "episode_returns": np.asarray(self.completed_returns, np.float32),
        }


# -- learner -----------------------------------------------------------------


def compute_gae(rewards, values, dones, last_value, gamma, lam):
    adv = np.zeros_like(rewards)
    gae = 0.0
    next_value = last_value
    for t in reversed(range(len(rewards))):
        nonterminal = 0.0 if dones[t] else 1.0
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        gae = delta + gamma * lam * nonterminal * gae
        adv[t] = gae
        next_value = values[t]
    returns = adv + values
    return adv, returns


@dataclass
class PPOConfig:
    env_factory: Callable = CartPoleEnv
    num_env_runners: int = 2
    rollout_steps: int = 256          # per runner per iteration
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-3
    num_sgd_epochs: int = 6
    minibatch_size: int = 128
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    hidden: int = 64
    seed: int = 0


class PPO:
    """Algorithm driver (reference Algorithm.train() shape)."""

    def __init__(self, config: PPOConfig = PPOConfig()):
        self.config = config
        env = config.env_factory()
        key = jax.random.PRNGKey(config.seed)
        self.params = init_policy(
            key, env.observation_size, env.num_actions, config.hidden
        )
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.runners = [
            EnvRunner.remote(config.env_factory, config.seed + 100 + i)
            for i in range(config.num_env_runners)
        ]
        self._key = key
        self.iteration = 0

        cfg = config

        @jax.jit
        def sgd_step(params, opt_state, batch):
            def loss_fn(params):
                logits, values = policy_forward(params, batch["obs"])
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, batch["actions"][:, None], 1
                )[:, 0]
                ratio = jnp.exp(logp - batch["logp"])
                adv = batch["advantages"]
                surr = jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1 - cfg.clip_eps, 1 + cfg.clip_eps) * adv,
                )
                pi_loss = -jnp.mean(surr)
                vf_loss = jnp.mean((values - batch["returns"]) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
                )
                total = (
                    pi_loss
                    + cfg.vf_coeff * vf_loss
                    - cfg.entropy_coeff * entropy
                )
                return total, (pi_loss, vf_loss, entropy)

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            updates, opt_state = self.optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._sgd_step = sgd_step

    def train(self) -> Dict[str, Any]:
        """One iteration: parallel rollouts → GAE → minibatch SGD epochs."""
        cfg = self.config
        self.iteration += 1
        refs = [
            r.rollout.remote(self.params, cfg.rollout_steps)
            for r in self.runners
        ]
        segments = ray_tpu.get(refs)

        obs, acts, logps, advs, rets, ep_returns = [], [], [], [], [], []
        for seg in segments:
            adv, ret = compute_gae(
                seg["rewards"], seg["values"], seg["dones"],
                seg["last_value"], cfg.gamma, cfg.gae_lambda,
            )
            obs.append(seg["obs"])
            acts.append(seg["actions"])
            logps.append(seg["logp"])
            advs.append(adv)
            rets.append(ret)
            ep_returns.extend(seg["episode_returns"].tolist())
        batch = {
            "obs": np.concatenate(obs),
            "actions": np.concatenate(acts),
            "logp": np.concatenate(logps),
            "advantages": np.concatenate(advs),
            "returns": np.concatenate(rets),
        }
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)

        n = len(batch["obs"])
        rng = np.random.default_rng(cfg.seed + self.iteration)
        loss = pi_loss = vf_loss = entropy = 0.0
        for _ in range(cfg.num_sgd_epochs):
            order = rng.permutation(n)
            for i in range(0, n, cfg.minibatch_size):
                idx = order[i : i + cfg.minibatch_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self.params, self.opt_state, loss, aux = self._sgd_step(
                    self.params, self.opt_state, mb
                )
                pi_loss, vf_loss, entropy = aux
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(ep_returns)) if ep_returns else float("nan")
            ),
            "num_episodes": len(ep_returns),
            "num_env_steps": n,
            "total_loss": float(loss),
            "policy_loss": float(pi_loss),
            "vf_loss": float(vf_loss),
            "entropy": float(entropy),
        }

    def save(self, path: str):
        from ray_tpu.train.checkpoint import Checkpoint

        return Checkpoint.from_state({"params": self.params}, path)

    def restore(self, path: str):
        from ray_tpu.train.checkpoint import Checkpoint

        self.params = Checkpoint(path).load_state()["params"]
