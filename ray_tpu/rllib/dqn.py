"""DQN: epsilon-greedy env runners -> replay buffer actor -> jitted
double-DQN learner with a periodically synced target network.

Reference shape: rllib/algorithms/dqn/ (replay buffer + target network +
TD loss); rebuilt on the framework's actor/object plane with a pure-jax
Q-network and one jitted sgd_step.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from .cartpole import CartPoleEnv
from .replay import ReplayBuffer


def init_qnet(key, obs_size: int, num_actions: int, hidden: int = 64):
    from .ppo import dense_init as dense

    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "l1": dense(k1, obs_size, hidden),
        "l2": dense(k2, hidden, hidden),
        "out": dense(k3, hidden, num_actions),
    }


def q_forward(params, obs):
    h = jnp.tanh(obs @ params["l1"]["w"] + params["l1"]["b"])
    h = jnp.tanh(h @ params["l2"]["w"] + params["l2"]["b"])
    return h @ params["out"]["w"] + params["out"]["b"]


@ray_tpu.remote
class DQNRunner:
    """Steps the env epsilon-greedily, shipping transitions to the replay
    buffer actor (ApeX actor analog: acting decoupled from learning)."""

    def __init__(self, env_factory: Callable, buffer, seed: int):
        self.env = env_factory()
        self.buffer = buffer
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.env.reset(seed=seed)
        self.episode_return = 0.0

    def collect(
        self, params, num_steps: int, eps: float
    ) -> Dict[str, Any]:
        obs_b, act_b, rew_b, nobs_b, done_b = [], [], [], [], []
        returns: List[float] = []
        for _ in range(num_steps):
            if self.rng.random() < eps:
                action = int(self.rng.integers(self.env.num_actions))
            else:
                q = q_forward(params, jnp.asarray(self.obs[None]))
                action = int(np.asarray(jnp.argmax(q[0])))
            nobs, reward, term, trunc, _ = self.env.step(action)
            obs_b.append(self.obs)
            act_b.append(action)
            rew_b.append(reward)
            nobs_b.append(nobs)
            done_b.append(term)  # bootstrap through time-limit truncation
            self.episode_return += reward
            if term or trunc:
                returns.append(self.episode_return)
                self.episode_return = 0.0
                self.obs, _ = self.env.reset()
            else:
                self.obs = nobs
        size = ray_tpu.get(
            self.buffer.add.remote(
                {
                    "obs": np.asarray(obs_b, np.float32),
                    "actions": np.asarray(act_b, np.int32),
                    "rewards": np.asarray(rew_b, np.float32),
                    "next_obs": np.asarray(nobs_b, np.float32),
                    "dones": np.asarray(done_b, np.bool_),
                }
            )
        )
        return {
            "episode_returns": returns,
            "steps": num_steps,
            "buffer_size": size,
        }


@dataclass
class DQNConfig:
    env_factory: Callable = CartPoleEnv
    num_env_runners: int = 2
    rollout_steps: int = 128        # per runner per iteration
    buffer_capacity: int = 20_000
    batch_size: int = 128
    sgd_steps_per_iter: int = 32
    gamma: float = 0.99
    lr: float = 1e-3
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_iters: int = 12
    target_sync_every: int = 2      # iterations between target syncs
    hidden: int = 64
    seed: int = 0


class DQN:
    """Algorithm driver (reference Algorithm.train() shape)."""

    def __init__(self, config: DQNConfig = DQNConfig()):
        self.config = config
        env = config.env_factory()
        key = jax.random.PRNGKey(config.seed)
        self.params = init_qnet(
            key, env.observation_size, env.num_actions, config.hidden
        )
        # leaves are immutable jax arrays; sharing them IS the snapshot
        # (apply_updates replaces leaves, never mutates)
        self.target_params = self.params
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.buffer = ReplayBuffer.remote(config.buffer_capacity, config.seed)
        self.runners = [
            DQNRunner.remote(
                config.env_factory, self.buffer, config.seed + 10 + i
            )
            for i in range(config.num_env_runners)
        ]
        self.iteration = 0
        cfg = config

        @jax.jit
        def sgd_step(params, target_params, opt_state, batch):
            def loss_fn(params):
                q = q_forward(params, batch["obs"])
                q_taken = jnp.take_along_axis(
                    q, batch["actions"][:, None], 1
                )[:, 0]
                # double DQN: online net picks, target net evaluates
                next_online = q_forward(params, batch["next_obs"])
                next_act = jnp.argmax(next_online, axis=-1)
                next_target = q_forward(target_params, batch["next_obs"])
                next_q = jnp.take_along_axis(
                    next_target, next_act[:, None], 1
                )[:, 0]
                target = batch["rewards"] + cfg.gamma * next_q * (
                    1.0 - batch["dones"].astype(jnp.float32)
                )
                td = q_taken - jax.lax.stop_gradient(target)
                return jnp.mean(optax.huber_loss(td))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._sgd_step = sgd_step

    def _epsilon(self) -> float:
        """Linear schedule; the FIRST iteration explores at eps_start."""
        cfg = self.config
        frac = min(
            1.0, (self.iteration - 1) / max(1, cfg.eps_decay_iters)
        )
        return cfg.eps_start + frac * (cfg.eps_end - cfg.eps_start)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        self.iteration += 1
        eps = self._epsilon()
        stats = ray_tpu.get(
            [
                r.collect.remote(self.params, cfg.rollout_steps, eps)
                for r in self.runners
            ]
        )
        ep_returns = [x for s in stats for x in s["episode_returns"]]
        loss = float("nan")
        sgd_done = 0
        for _ in range(cfg.sgd_steps_per_iter):
            batch = ray_tpu.get(self.buffer.sample.remote(cfg.batch_size))
            if batch is None:
                break
            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            self.params, self.opt_state, loss_j = self._sgd_step(
                self.params, self.target_params, self.opt_state, jb
            )
            loss = float(loss_j)
            sgd_done += 1
        if self.iteration % cfg.target_sync_every == 0:
            self.target_params = self.params
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(ep_returns)) if ep_returns else float("nan")
            ),
            "num_episodes": len(ep_returns),
            "num_env_steps": cfg.rollout_steps * cfg.num_env_runners,
            "buffer_size": stats[-1]["buffer_size"],
            "epsilon": eps,
            "td_loss": loss,
            "sgd_steps": sgd_done,
        }

    def save(self, path: str):
        from ray_tpu.train.checkpoint import Checkpoint

        return Checkpoint.from_state({"params": self.params}, path)

    def restore(self, path: str):
        from ray_tpu.train.checkpoint import Checkpoint

        self.params = Checkpoint(path).load_state()["params"]
        self.target_params = self.params
