"""IMPALA: asynchronous rollouts + V-trace off-policy correction.

Reference shape: rllib/algorithms/impala/ — actors stream rollouts
collected under a stale behavior policy while the learner updates
continuously; importance-weight clipping (V-trace, Espeholt et al. 2018)
corrects the off-policyness. Here: env-runner actors keep one rollout in
flight each (ray_tpu.wait drives the async loop), and the learner is one
jitted update whose V-trace targets are computed inside the jit with a
lax.scan (TPU-friendly: no host recursion).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from .cartpole import CartPoleEnv
from .ppo import init_policy, policy_forward


@ray_tpu.remote
class ImpalaRunner:
    """Collects fixed-length segments under whatever params it was last
    handed (the learner may have moved on — that lag is the point)."""

    def __init__(self, env_factory: Callable, seed: int):
        self.env = env_factory()
        self.rng = np.random.default_rng(seed)
        self.obs, _ = self.env.reset(seed=seed)
        self.episode_return = 0.0

    def rollout(self, params, num_steps: int) -> Dict[str, np.ndarray]:
        obs_b, act_b, rew_b, done_b, blogp_b = [], [], [], [], []
        returns: List[float] = []
        for _ in range(num_steps):
            logits, _ = policy_forward(params, jnp.asarray(self.obs[None]))
            probs = np.asarray(jax.nn.softmax(logits[0]))
            action = int(self.rng.choice(len(probs), p=probs / probs.sum()))
            blogp = float(np.log(probs[action] + 1e-9))
            nobs, reward, term, trunc, _ = self.env.step(action)
            obs_b.append(self.obs)
            act_b.append(action)
            rew_b.append(reward)
            done_b.append(term or trunc)
            blogp_b.append(blogp)
            self.episode_return += reward
            if term or trunc:
                returns.append(self.episode_return)
                self.episode_return = 0.0
                self.obs, _ = self.env.reset()
            else:
                self.obs = nobs
        return {
            "obs": np.asarray(obs_b, np.float32),
            "actions": np.asarray(act_b, np.int32),
            "rewards": np.asarray(rew_b, np.float32),
            "dones": np.asarray(done_b, np.bool_),
            "behavior_logp": np.asarray(blogp_b, np.float32),
            "bootstrap_obs": np.asarray(self.obs, np.float32),
            "episode_returns": np.asarray(returns, np.float32),
        }


@dataclass
class ImpalaConfig:
    env_factory: Callable = CartPoleEnv
    num_env_runners: int = 2
    rollout_steps: int = 128
    gamma: float = 0.99
    lr: float = 3e-3
    rho_clip: float = 1.0       # V-trace importance-weight clip
    c_clip: float = 1.0
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    hidden: int = 64
    seed: int = 0
    updates_per_iter: int = 4   # segments consumed per train() call


class IMPALA:
    """Algorithm driver (reference Algorithm.train() shape) with an
    asynchronous rollout pipeline: every runner always has a segment in
    flight; train() consumes whichever finish first."""

    def __init__(self, config: ImpalaConfig = ImpalaConfig()):
        self.config = config
        env = config.env_factory()
        key = jax.random.PRNGKey(config.seed)
        self.params = init_policy(
            key, env.observation_size, env.num_actions, config.hidden
        )
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.runners = [
            ImpalaRunner.remote(config.env_factory, config.seed + 50 + i)
            for i in range(config.num_env_runners)
        ]
        self.iteration = 0
        self._in_flight: Dict[str, Any] = {}  # ref hex -> runner
        cfg = config

        @jax.jit
        def update(params, opt_state, batch):
            def loss_fn(params):
                logits, values = policy_forward(params, batch["obs"])
                _, bootstrap_v = policy_forward(
                    params, batch["bootstrap_obs"][None]
                )
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, batch["actions"][:, None], 1
                )[:, 0]
                rho = jnp.exp(logp - batch["behavior_logp"])
                rho_c = jnp.minimum(rho, cfg.rho_clip)
                c_c = jnp.minimum(rho, cfg.c_clip)
                discounts = cfg.gamma * (
                    1.0 - batch["dones"].astype(jnp.float32)
                )
                values_sg = jax.lax.stop_gradient(values)
                next_values = jnp.concatenate(
                    [values_sg[1:], bootstrap_v]
                )
                deltas = rho_c * (
                    batch["rewards"] + discounts * next_values - values_sg
                )

                # v-trace targets via reverse scan (in-jit, no host loop):
                # vs_t = V_t + delta_t + discount_t * c_t * (vs_{t+1} - V_{t+1})
                def body(acc, x):
                    delta_t, disc_t, c_t = x
                    acc = delta_t + disc_t * c_t * acc
                    return acc, acc

                _, adv_rev = jax.lax.scan(
                    body,
                    jnp.float32(0.0),
                    (deltas[::-1], discounts[::-1], c_c[::-1]),
                )
                vs_minus_v = adv_rev[::-1]
                vs = values_sg + vs_minus_v
                next_vs = jnp.concatenate([vs[1:], bootstrap_v])
                pg_adv = rho_c * (
                    batch["rewards"] + discounts * next_vs - values_sg
                )
                pi_loss = -jnp.mean(logp * jax.lax.stop_gradient(pg_adv))
                vf_loss = jnp.mean((values - jax.lax.stop_gradient(vs)) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
                )
                total = (
                    pi_loss
                    + cfg.vf_coeff * vf_loss
                    - cfg.entropy_coeff * entropy
                )
                return total, (pi_loss, vf_loss, entropy)

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            updates, opt_state = self.optimizer.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = update

    def _launch(self, runner) -> None:
        ref = runner.rollout.remote(self.params, self.config.rollout_steps)
        self._in_flight[ref] = runner

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        self.iteration += 1
        for r in self.runners:
            if r not in self._in_flight.values():
                self._launch(r)
        ep_returns: List[float] = []
        loss = pi_loss = vf_loss = entropy = float("nan")
        consumed = 0
        while consumed < cfg.updates_per_iter:
            ready, _ = ray_tpu.wait(
                list(self._in_flight), num_returns=1, timeout=120
            )
            if not ready:
                break
            ref = ready[0]
            runner = self._in_flight.pop(ref)
            seg = ray_tpu.get(ref)
            self._launch(runner)  # keep the pipeline full
            batch = {k: jnp.asarray(v) for k, v in seg.items()
                     if k != "episode_returns"}
            self.params, self.opt_state, loss_j, aux = self._update(
                self.params, self.opt_state, batch
            )
            loss = float(loss_j)
            pi_loss, vf_loss, entropy = (float(x) for x in aux)
            ep_returns.extend(seg["episode_returns"].tolist())
            consumed += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": (
                float(np.mean(ep_returns)) if ep_returns else float("nan")
            ),
            "num_episodes": len(ep_returns),
            "num_env_steps": consumed * cfg.rollout_steps,
            "total_loss": loss,
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    def save(self, path: str):
        from ray_tpu.train.checkpoint import Checkpoint

        return Checkpoint.from_state({"params": self.params}, path)

    def restore(self, path: str):
        from ray_tpu.train.checkpoint import Checkpoint

        self.params = Checkpoint(path).load_state()["params"]
