"""ray_tpu.rllib — RL training: env-runner actors + jax learner.

Analog of the reference RLlib core loop (/root/reference/rllib/algorithms/
algorithm.py + core/learner/learner_group.py + EnvRunnerGroup): parallel
env-runner actors collect rollouts under the current policy; a jitted
learner applies GAE + the PPO clipped surrogate with optax. Model compute is
pure jax (pjit-able for larger policies).
"""
from .cartpole import CartPoleEnv  # noqa: F401
from .dqn import DQN, DQNConfig  # noqa: F401
from .impala import IMPALA, ImpalaConfig  # noqa: F401
from .ppo import PPO, PPOConfig  # noqa: F401
from .replay import ReplayBuffer  # noqa: F401
