"""Replay buffer actor — the distributed experience store for off-policy
algorithms (reference: ray/rllib/utils/replay_buffers/, run as actors by
ApeX-style setups). A ring of preallocated numpy arrays; env runners add
transition batches, the learner samples uniformly."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import ray_tpu


@ray_tpu.remote
class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self.rng = np.random.default_rng(seed)
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0

    def add(self, batch: Dict[str, np.ndarray]) -> int:
        n = len(batch["obs"])
        if self._store is None:
            self._store = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()
            }
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        return self._size

    def sample(self, batch_size: int) -> Optional[Dict[str, np.ndarray]]:
        if self._size < batch_size:
            return None
        idx = self.rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._store.items()}

    def size(self) -> int:
        return self._size
