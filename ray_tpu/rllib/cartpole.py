"""Self-contained CartPole-v1 (gymnasium API; no external dependency).

Standard cart-pole dynamics (Barto-Sutton-Anderson), matching the classic
control task the reference's RLlib suites benchmark against.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class CartPoleEnv:
    observation_size = 4
    num_actions = 2

    def __init__(self, seed: int = 0, max_steps: int = 500):
        self.rng = np.random.default_rng(seed)
        self.max_steps = max_steps
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.state: Optional[np.ndarray] = None
        self.steps = 0

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self.steps = 0
        return self.state.copy(), {}

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, bool, dict]:
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costh, sinth = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (
            force + polemass_length * theta_dot**2 * sinth
        ) / total_mass
        thetaacc = (self.gravity * sinth - costh * temp) / (
            self.length
            * (4.0 / 3.0 - self.masspole * costh**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costh / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot], dtype=np.float32)
        self.steps += 1
        terminated = bool(
            abs(x) > self.x_threshold or abs(theta) > self.theta_threshold
        )
        truncated = self.steps >= self.max_steps
        return self.state.copy(), 1.0, terminated, truncated, {}
