"""ray_tpu — a TPU-native distributed compute framework.

A ground-up redesign of the capabilities of the reference Ray fork
(/root/reference, Ray ~2.54): tasks, actors, objects, placement groups,
cluster scheduling, autoscaling, and the AI libraries — built TPU-first.
The cluster scheduler itself is a set of batched JAX programs
(ray_tpu.scheduler); model compute is jax/pjit/pallas over device meshes.
"""
from ray_tpu._version import __version__  # noqa: F401

from ray_tpu.core.api import (  # noqa: F401
    GetTimeoutError,
    ObjectLostError,
    ObjectRef,
    OwnerDiedError,
    TaskError,
    actor_exited,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from ray_tpu.core.placement_group import (  # noqa: F401
    placement_group,
    placement_group_table,
    remove_placement_group,
)

# method decorator for actor method options
from ray_tpu.core.actor import method  # noqa: F401
