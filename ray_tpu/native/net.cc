// C hot path for the cross-node data plane: scatter-gather socket I/O.
//
// The same-node fast paths (shm arena views, ring pairs) stop at the
// node boundary; this file is the wire under cluster/transport.py — the
// worker<->worker data sockets that carry RTP5 frames (wire.cc) across
// nodes. What moves to C is the syscall loop: one rtpu_net_send_vec call
// sendmsg()s an arbitrary iovec of frame parts (header + arena views)
// with NO joins or intermediate copies on the send side, and
// rtpu_net_recv_exact / rtpu_net_recv_vec land the payload straight into
// the receiving arena's pages (put_frames-style scatter-writes) instead
// of through per-chunk Python bytes.
//
// Pure C ABI consumed via ctypes (no pybind11, per the environment
// constraints) — same convention as object_store.cc / wire.cc. All
// functions return >= 0 on success and -errno on failure; partial
// sends/recvs are retried internally until the full byte count moved or
// the peer/timeout broke the transfer.
#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMaxIov = 64;  // well under IOV_MAX on every target

int set_timeout_ms(int fd, int which, int timeout_ms) {
  struct timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  return setsockopt(fd, SOL_SOCKET, which, &tv, sizeof(tv));
}

// Dark-plane counter slots (native/counters.py CounterBlock, same page
// wire.cc registers). Relaxed atomics; slot indices are ABI shared with
// counters.py SLOTS.
long long* g_counters = nullptr;
constexpr int kSlotTxBytes = 3;
constexpr int kSlotTxFrames = 4;
constexpr int kSlotRxBytes = 5;

inline void bump(int slot, long long v) {
  if (g_counters)
    __atomic_add_fetch(&g_counters[slot], v, __ATOMIC_RELAXED);
}

}  // namespace

extern "C" {

// Register the shm counter page (nullptr disables).
void rtpu_net_set_counters(long long* slots) { g_counters = slots; }

// Bind + listen on host:port (port 0 = ephemeral). Returns the listen fd
// or -errno.
int rtpu_net_listen(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    int err = errno;
    close(fd);
    return -err;
  }
  return fd;
}

// The port a listen fd actually bound (ephemeral-port discovery).
int rtpu_net_local_port(int fd) {
  struct sockaddr_in addr;
  socklen_t len = sizeof(addr);
  if (getsockname(fd, reinterpret_cast<struct sockaddr*>(&addr), &len) != 0)
    return -errno;
  return ntohs(addr.sin_port);
}

// Accept one connection (bounded by timeout_ms; <=0 blocks). Returns the
// connection fd, -EAGAIN on timeout, or -errno. TCP_NODELAY is set: the
// protocol is request/response and a delayed header ACK would serialize
// every stripe on Nagle.
int rtpu_net_accept(int listen_fd, int timeout_ms) {
  if (timeout_ms > 0 &&
      set_timeout_ms(listen_fd, SO_RCVTIMEO, timeout_ms) != 0)
    return -errno;
  int fd = accept(listen_fd, nullptr, nullptr);
  if (fd < 0)
    return (errno == EAGAIN || errno == EWOULDBLOCK) ? -EAGAIN : -errno;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Connect to host:port with a connect timeout. Returns the fd or -errno.
int rtpu_net_connect(const char* host, int port, int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -errno;
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -EINVAL;
  }
  // SO_SNDTIMEO bounds a blocking connect() on Linux — no nonblocking
  // dance needed for a data-plane dial with second-scale budgets
  if (timeout_ms > 0) set_timeout_ms(fd, SO_SNDTIMEO, timeout_ms);
  if (connect(fd, reinterpret_cast<struct sockaddr*>(&addr), sizeof(addr)) !=
      0) {
    int err = errno;
    close(fd);
    return -((err == EAGAIN || err == EWOULDBLOCK) ? ETIMEDOUT : err);
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Per-operation I/O deadline for an established connection (applies to
// every subsequent send/recv loop iteration).
int rtpu_net_set_timeout(int fd, int timeout_ms) {
  if (set_timeout_ms(fd, SO_RCVTIMEO, timeout_ms) != 0) return -errno;
  if (set_timeout_ms(fd, SO_SNDTIMEO, timeout_ms) != 0) return -errno;
  return 0;
}

// Gather-send the whole iovec (bufs[i], lens[i]) x n. One sendmsg per
// kernel round; partial writes advance the iovec in place — frame parts
// (header bytes + arena views) go out with ZERO user-space joins/copies.
// Returns total bytes sent or -errno.
int64_t rtpu_net_send_vec(int fd, const void* const* bufs,
                          const uint64_t* lens, uint32_t n) {
  struct iovec iov[kMaxIov];
  uint64_t total = 0;
  uint32_t idx = 0;
  uint64_t consumed0 = 0;  // bytes of bufs[idx] already sent
  while (idx < n) {
    uint32_t cnt = 0;
    for (uint32_t i = idx; i < n && cnt < kMaxIov; ++i) {
      uint64_t skip = (i == idx) ? consumed0 : 0;
      if (lens[i] <= skip) {
        if (i == idx) {  // fully-sent head segment: advance past it
          ++idx;
          consumed0 = 0;
        }
        continue;
      }
      iov[cnt].iov_base =
          const_cast<uint8_t*>(static_cast<const uint8_t*>(bufs[i]) + skip);
      iov[cnt].iov_len = static_cast<size_t>(lens[i] - skip);
      ++cnt;
    }
    if (cnt == 0) break;  // only empty segments remained
    struct msghdr msg;
    std::memset(&msg, 0, sizeof(msg));
    msg.msg_iov = iov;
    msg.msg_iovlen = cnt;
    ssize_t sent = sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (sent < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    total += static_cast<uint64_t>(sent);
    // advance (idx, consumed0) past what the kernel took
    uint64_t left = static_cast<uint64_t>(sent);
    while (left > 0 && idx < n) {
      uint64_t avail = lens[idx] - consumed0;
      if (left >= avail) {
        left -= avail;
        ++idx;
        consumed0 = 0;
      } else {
        consumed0 += left;
        left = 0;
      }
    }
    while (idx < n && lens[idx] == consumed0) {  // skip exhausted heads
      ++idx;
      consumed0 = 0;
    }
  }
  bump(kSlotTxBytes, static_cast<long long>(total));
  bump(kSlotTxFrames, 1);
  return static_cast<int64_t>(total);
}

// Receive exactly len bytes into buf (e.g. straight into an arena
// offset). Returns len, 0 if the peer closed before any byte, or -errno
// (-EAGAIN = timeout; a mid-stream close returns -ECONNRESET so a
// half-delivered stripe can never read as success).
int64_t rtpu_net_recv_exact(int fd, void* buf, uint64_t len) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  uint64_t got = 0;
  while (got < len) {
    ssize_t r = recv(fd, p + got, static_cast<size_t>(len - got), 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      return (errno == EAGAIN || errno == EWOULDBLOCK) ? -EAGAIN : -errno;
    }
    if (r == 0) return got == 0 ? 0 : -ECONNRESET;
    got += static_cast<uint64_t>(r);
  }
  bump(kSlotRxBytes, static_cast<long long>(len));
  return static_cast<int64_t>(len);
}

// Scatter-receive exactly sum(lens) bytes across the iovec — the
// receiving half of send_vec (payload lands across arena segments with
// no staging buffer). Returns total bytes or -errno (mid-stream close =
// -ECONNRESET, same contract as recv_exact).
int64_t rtpu_net_recv_vec(int fd, void* const* bufs, const uint64_t* lens,
                          uint32_t n) {
  int64_t total = 0;
  for (uint32_t i = 0; i < n; ++i) {
    if (lens[i] == 0) continue;
    int64_t rc = rtpu_net_recv_exact(fd, bufs[i], lens[i]);
    if (rc < 0) return rc;
    if (static_cast<uint64_t>(rc) != lens[i]) return -ECONNRESET;
    total += rc;
  }
  return total;
}

int rtpu_net_close(int fd) {
  return close(fd) == 0 ? 0 : -errno;
}

}  // extern "C"
