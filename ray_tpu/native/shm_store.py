"""ctypes binding for the C++ shared-memory object store.

Zero-copy path: ``put_numpy``/``put_frames`` write into the mmap arena;
``get_numpy``/``get_view`` return VIEWS over the same shared pages — any
process that opens the same store file sees the bytes without a copy (the
plasma fd-passing model, by shared file instead of fd fling).

View lifetime: ``get_view`` pins the object (shared-memory refcount, so
the pin is visible across processes); a ``delete`` that lands while views
are outstanding defers the arena free until the last view's finalizer
releases the pin (zombie entries, object_store.cc) — a mapped numpy view
can never observe its pages being reused.
"""
from __future__ import annotations

import ctypes
import json
import os
import tempfile
import weakref
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .build import build_native

_ID_LEN = 28


def pin_log_path(arena_path: str, pid: int) -> str:
    return f"{arena_path}.pins.{pid}"


class PinLog:
    """Append-only, crash-durable sidecar recording one process's
    outstanding ``get_view`` pins as ``P <id> <offset>`` / ``R <id>
    <offset>`` lines.

    The shared-memory refcount lives in the arena header, so a reader
    that dies (SIGKILL) leaks its pins — the arena can't know. The log
    lets the AGENT net out the dead reader's outstanding pins and
    release them (id, offset)-precise. Ordering is chosen so a crash in
    any window can only leak (bounded, reclaimed at the next arena
    restart), never double-release: the pin record lands AFTER the pin
    is taken, and the release record lands BEFORE the refcount drops —
    replay therefore never releases a share the process still held."""

    def __init__(self, path: str):
        self.path = path
        # O_APPEND: each record is one short write, atomic per POSIX
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o600)

    def pin(self, oid: bytes, offset: int) -> None:
        try:
            os.write(self._fd, b"P %s %d\n" % (oid, offset))
        except OSError:
            pass  # best-effort: a full disk must not fail reads

    def release(self, oid: bytes, offset: int) -> None:
        try:
            os.write(self._fd, b"R %s %d\n" % (oid, offset))
        except OSError:
            pass

    def close(self) -> None:
        # the file itself is NOT unlinked here: even a clean exit can
        # leave un-finalized views, and the agent's death replay is what
        # nets the log out and removes it
        try:
            os.close(self._fd)
        except OSError:
            pass


def read_outstanding_pins(path: str):
    """Net a pin log down to its outstanding ``(id, offset) -> count``
    entries. Tolerates a torn trailing record (crash mid-write)."""
    from collections import Counter

    out: "Counter" = Counter()
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return out
    for line in data.split(b"\n"):
        parts = line.split()
        if len(parts) != 3 or parts[0] not in (b"P", b"R"):
            continue
        try:
            key = (bytes(parts[1]), int(parts[2]))
        except ValueError:
            continue
        out[key] += 1 if parts[0] == b"P" else -1
    return out


class NativeObjectStore:
    def __init__(
        self,
        path: Optional[str] = None,
        capacity: int = 1 << 28,  # 256 MiB default arena
        table_slots: int = 1 << 14,
        create: bool = True,
    ):
        lib = ctypes.CDLL(build_native("objstore"))
        lib.rtpu_store_open.restype = ctypes.c_void_p
        lib.rtpu_store_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.rtpu_store_create.restype = ctypes.c_int64
        lib.rtpu_store_create.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        for fn in ("rtpu_store_seal", "rtpu_store_release", "rtpu_store_delete"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_store_release_at.restype = ctypes.c_int
        lib.rtpu_store_release_at.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.rtpu_store_get.restype = ctypes.c_int
        lib.rtpu_store_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtpu_store_base.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_zombie_count.restype = ctypes.c_uint64
        lib.rtpu_store_zombie_count.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self.path = path or os.path.join(
            tempfile.gettempdir(), f"ray_tpu_store_{os.getpid()}.shm"
        )
        self._owns_file = path is None
        self._unlinked = False
        self._h = lib.rtpu_store_open(
            self.path.encode(), capacity, table_slots, 1 if create else 0
        )
        if not self._h:
            raise OSError(f"failed to open native store at {self.path}")
        # per-pid crash-safe registry of outstanding view pins (see
        # PinLog): enabled by long-lived readers (workers) so the agent
        # can release a SIGKILLed reader's pins release_at-precise
        # instead of leaking arena zombies until restart
        self._pin_log: Optional[PinLog] = None

    def enable_pin_tracking(self) -> None:
        """Track this process's ``get_view`` pins in a crash-durable
        per-pid sidecar (``<arena>.pins.<pid>``). The agent replays the
        sidecar when this process dies and releases every outstanding
        pin, so a SIGKILLed reader no longer leaks zombie entries."""
        if self._pin_log is None:
            self._pin_log = PinLog(pin_log_path(self.path, os.getpid()))

    # -- raw bytes ------------------------------------------------------
    def _norm_id(self, object_id: str) -> bytes:
        b = object_id.encode()
        if len(b) != _ID_LEN:
            # non-canonical ids get a collision-safe digest form
            import hashlib

            b = hashlib.sha256(b).hexdigest()[:_ID_LEN].encode()
        return b

    def put_bytes(self, object_id: str, data: bytes) -> None:
        self.put_frames(object_id, [data])

    def put_frames(self, object_id: str, frames: Sequence) -> int:
        """Scatter-write ``frames`` (bytes / memoryviews) as one object —
        the out-of-band wire format streams straight into shared memory
        with a single gather copy. Returns the object's total size."""
        sizes = [
            f.nbytes if isinstance(f, memoryview) else len(f) for f in frames
        ]
        total = sum(sizes)
        oid = self._norm_id(object_id)
        off = self._lib.rtpu_store_create(self._h, oid, total)
        if off == -2:
            raise KeyError(f"object {object_id} already in store")
        if off < 0:
            raise MemoryError(f"native store allocation failed ({off})")
        base = self._lib.rtpu_store_base(self._h)
        dest = memoryview(
            (ctypes.c_char * total).from_address(
                ctypes.addressof(base.contents) + off
            )
        ).cast("B")
        pos = 0
        for f, n in zip(frames, sizes):
            if n == 0:
                continue
            src = f if isinstance(f, memoryview) else memoryview(f)
            dest[pos : pos + n] = src.cast("B")
            pos += n
        self._lib.rtpu_store_seal(self._h, oid)
        return total

    # -- staged puts (cross-node receive path) --------------------------
    def begin_put(self, object_id: str, total: int) -> memoryview:
        """Allocate an UNSEALED arena entry and hand back a writable view
        over its pages — the cross-node receive path scatter-writes
        stripes straight into shared memory (put_frames split into
        allocate / land / seal so the landing can happen from socket
        recv loops). Finish with :meth:`commit_put` (seal) or
        :meth:`abort_put` (free); readers cannot observe the entry until
        the commit."""
        oid = self._norm_id(object_id)
        off = self._lib.rtpu_store_create(self._h, oid, total)
        if off == -2:
            raise KeyError(f"object {object_id} already in store")
        if off < 0:
            raise MemoryError(f"native store allocation failed ({off})")
        base = self._lib.rtpu_store_base(self._h)
        return memoryview(
            (ctypes.c_char * total).from_address(
                ctypes.addressof(base.contents) + off
            )
        ).cast("B")

    def commit_put(self, object_id: str) -> None:
        self._lib.rtpu_store_seal(self._h, self._norm_id(object_id))

    def abort_put(self, object_id: str) -> None:
        """Free a staged entry whose transfer failed. Deletes the
        UNSEALED entry directly (delete tombstones any entry whose only
        share is the creator's) — the half-landed bytes are never
        observable: get refuses unsealed entries, and no seal ever
        happens on this path."""
        try:
            self._lib.rtpu_store_delete(self._h, self._norm_id(object_id))
        except Exception:  # noqa: BLE001 - best-effort reclamation
            pass

    def get_buffer(self, object_id: str) -> Tuple[int, int]:
        oid = self._norm_id(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_store_get(
            self._h, oid, ctypes.byref(off), ctypes.byref(size)
        )
        if rc == -1:
            raise KeyError(object_id)
        if rc == -2:
            raise BlockingIOError(f"object {object_id} not sealed yet")
        if rc != 0:
            raise OSError(f"store get failed ({rc})")
        return off.value, size.value

    def get_bytes(self, object_id: str) -> bytes:
        # every release in this class goes through release_at: an id-only
        # release cannot find an entry that went zombie under our pin (a
        # concurrent delete/spill) and could decrement a same-id
        # SUCCESSOR's creator share instead — (id, offset) is precise
        off, size = self.get_buffer(object_id)
        base = self._lib.rtpu_store_base(self._h)
        out = ctypes.string_at(ctypes.addressof(base.contents) + off, size)
        self._lib.rtpu_store_release_at(self._h, self._norm_id(object_id), off)
        return out

    def get_range(self, object_id: str, offset: int, length: int) -> bytes:
        """One chunk of an object (peer transfer slicing) — copies only
        the requested window."""
        off, size = self.get_buffer(object_id)
        try:
            if offset >= size:
                return b""
            n = min(length, size - offset)
            base = self._lib.rtpu_store_base(self._h)
            return ctypes.string_at(
                ctypes.addressof(base.contents) + off + offset, n
            )
        finally:
            self._lib.rtpu_store_release_at(
                self._h, self._norm_id(object_id), off
            )

    def get_view(self, object_id: str) -> memoryview:
        """Read-only zero-copy view over the object's shared pages.

        The object stays pinned (shared refcount) until every view/array
        derived from the returned memoryview is garbage-collected; a
        concurrent delete defers the arena free until then."""
        oid = self._norm_id(object_id)
        off, size = self.get_buffer(object_id)  # pins
        if self._pin_log is not None:
            # recorded AFTER the pin exists: a crash between the two can
            # only leak this one pin, never replay-release a live share
            self._pin_log.pin(oid, off)
        base = self._lib.rtpu_store_base(self._h)
        raw = (ctypes.c_uint8 * size).from_address(
            ctypes.addressof(base.contents) + off
        )
        # finalizer releases the pin when the LAST derived view dies (the
        # memoryview chain keeps `raw` alive); release_at is (id, offset)-
        # precise so a same-id reput can never absorb this release
        weakref.finalize(raw, self._release_pin, oid, off)
        return memoryview(raw).toreadonly()

    def _release_pin(self, oid: bytes, off: int) -> None:
        if self._h:
            if self._pin_log is not None:
                # logged BEFORE the refcount drops: a crash in between
                # leaks (reclaimed next restart) instead of letting the
                # agent's replay double-release a freed entry
                self._pin_log.release(oid, off)
            try:
                self._lib.rtpu_store_release_at(self._h, oid, off)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass

    def release_dead_pins(self, pid: int) -> int:
        """Replay a dead reader's pin log and release every pin it still
        held ((id, offset)-precise — exactly what its finalizers would
        have done). Returns the number of pins released; removes the
        log. The agent calls this from its worker-death path so a
        SIGKILLed reader's zombies are reclaimed immediately instead of
        at the next arena restart."""
        path = pin_log_path(self.path, pid)
        outstanding = read_outstanding_pins(path)
        released = 0
        for (oid, off), n in outstanding.items():
            for _ in range(max(0, n)):
                if self._lib.rtpu_store_release_at(self._h, oid, off) == 0:
                    released += 1
        try:
            os.unlink(path)
        except OSError:
            pass
        return released

    def zombie_count(self) -> int:
        """Entries deleted while readers still pinned them and not yet
        reclaimed. Nonzero after every reader released (or died and had
        its pin log replayed) means a leak; the chaos soak asserts 0."""
        return int(self._lib.rtpu_store_zombie_count(self._h))

    # -- zero-copy numpy ------------------------------------------------
    def put_numpy(self, object_id: str, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        header = json.dumps(
            {"dtype": arr.dtype.str, "shape": list(arr.shape)}
        ).encode()
        self.put_frames(
            object_id,
            [len(header).to_bytes(4, "little"), header, memoryview(arr).cast("B")],
        )

    def get_numpy(self, object_id: str) -> np.ndarray:
        """Returns a read-only view over the shared pages (no copy)."""
        mv = self.get_view(object_id)
        hlen = int.from_bytes(mv[:4], "little")
        meta = json.loads(bytes(mv[4 : 4 + hlen]))
        arr = np.frombuffer(
            mv, dtype=np.dtype(meta["dtype"]), offset=4 + hlen
        ).reshape(meta["shape"])
        return arr

    def object_size(self, object_id: str) -> int:
        off, size = self.get_buffer(object_id)
        self._lib.rtpu_store_release_at(self._h, self._norm_id(object_id), off)
        return size

    def delete(self, object_id: str) -> None:
        self._lib.rtpu_store_delete(self._h, self._norm_id(object_id))

    def contains(self, object_id: str) -> bool:
        try:
            off, _ = self.get_buffer(object_id)
            self._lib.rtpu_store_release_at(
                self._h, self._norm_id(object_id), off
            )
            return True
        except (KeyError, BlockingIOError):
            return False

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        num = ctypes.c_uint64()
        self._lib.rtpu_store_stats(
            self._h, ctypes.byref(cap), ctypes.byref(used), ctypes.byref(num)
        )
        return {
            "capacity": cap.value,
            "used": used.value,
            "num_objects": num.value,
        }

    def close(self, unlink: bool = False) -> None:
        if self._pin_log is not None:
            self._pin_log.close()
            self._pin_log = None
        if self._h:
            self._lib.rtpu_store_close(self._h)
            self._h = None
        # unlink exactly once: close(unlink=True) + __del__ used to race
        # a second unlink, and a path-sharing reader (worker) closing its
        # mapping must never take the agent's arena file with it
        if (unlink or self._owns_file) and not self._unlinked:
            self._unlinked = True
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def sweep_orphan_stores(tmpdir: Optional[str] = None) -> List[str]:
    """Remove ``ray_tpu_store_*.shm`` arenas / ``ray_tpu_spill_*`` dirs
    left by killed agents (chaos kills skip the unlink path). A file is
    an orphan when the pid embedded in its name is no longer alive; run
    at agent start so /tmp does not accrete a dead agent's arena per
    kill. Returns the paths removed."""
    import re
    import shutil

    tmpdir = tmpdir or tempfile.gettempdir()
    removed: List[str] = []
    try:
        names = os.listdir(tmpdir)
    except OSError:
        return removed
    pat = re.compile(r"^ray_tpu_(store|spill)_.*?(\d+)(\.shm)?$")
    for name in names:
        m = pat.match(name)
        if not m:
            continue
        pid = int(m.group(2))
        if pid <= 0 or _pid_alive(pid):
            continue
        path = os.path.join(tmpdir, name)
        try:
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.unlink(path)
            removed.append(path)
        except OSError:
            pass
    return removed


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True
