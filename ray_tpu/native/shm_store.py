"""ctypes binding for the C++ shared-memory object store.

Zero-copy path: ``put_numpy`` writes the array into the mmap arena;
``get_numpy`` returns an ndarray VIEW over the same shared pages — any
process that opens the same store file sees the bytes without a copy (the
plasma fd-passing model, by shared file instead of fd fling).
"""
from __future__ import annotations

import ctypes
import json
import os
import tempfile
from typing import Optional, Tuple

import numpy as np

from .build import build_native

_ID_LEN = 28


class NativeObjectStore:
    def __init__(
        self,
        path: Optional[str] = None,
        capacity: int = 1 << 28,  # 256 MiB default arena
        table_slots: int = 1 << 14,
        create: bool = True,
    ):
        lib = ctypes.CDLL(build_native("objstore"))
        lib.rtpu_store_open.restype = ctypes.c_void_p
        lib.rtpu_store_open.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.rtpu_store_create.restype = ctypes.c_int64
        lib.rtpu_store_create.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        for fn in ("rtpu_store_seal", "rtpu_store_release", "rtpu_store_delete"):
            getattr(lib, fn).restype = ctypes.c_int
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rtpu_store_get.restype = ctypes.c_int
        lib.rtpu_store_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtpu_store_base.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rtpu_store_close.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self.path = path or os.path.join(
            tempfile.gettempdir(), f"ray_tpu_store_{os.getpid()}.shm"
        )
        self._owns_file = path is None
        self._h = lib.rtpu_store_open(
            self.path.encode(), capacity, table_slots, 1 if create else 0
        )
        if not self._h:
            raise OSError(f"failed to open native store at {self.path}")

    # -- raw bytes ------------------------------------------------------
    def _norm_id(self, object_id: str) -> bytes:
        b = object_id.encode()
        if len(b) != _ID_LEN:
            # non-canonical ids get a collision-safe digest form
            import hashlib

            b = hashlib.sha256(b).hexdigest()[:_ID_LEN].encode()
        return b

    def put_bytes(self, object_id: str, data: bytes) -> None:
        oid = self._norm_id(object_id)
        off = self._lib.rtpu_store_create(self._h, oid, len(data))
        if off == -2:
            raise KeyError(f"object {object_id} already in store")
        if off < 0:
            raise MemoryError(f"native store allocation failed ({off})")
        base = self._lib.rtpu_store_base(self._h)
        ctypes.memmove(
            ctypes.addressof(base.contents) + off, data, len(data)
        )
        self._lib.rtpu_store_seal(self._h, oid)

    def get_buffer(self, object_id: str) -> Tuple[int, int]:
        oid = self._norm_id(object_id)
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.rtpu_store_get(
            self._h, oid, ctypes.byref(off), ctypes.byref(size)
        )
        if rc == -1:
            raise KeyError(object_id)
        if rc == -2:
            raise BlockingIOError(f"object {object_id} not sealed yet")
        if rc != 0:
            raise OSError(f"store get failed ({rc})")
        return off.value, size.value

    def get_bytes(self, object_id: str) -> bytes:
        off, size = self.get_buffer(object_id)
        base = self._lib.rtpu_store_base(self._h)
        out = ctypes.string_at(ctypes.addressof(base.contents) + off, size)
        self._lib.rtpu_store_release(self._h, self._norm_id(object_id))
        return out

    # -- zero-copy numpy ------------------------------------------------
    def put_numpy(self, object_id: str, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        header = json.dumps(
            {"dtype": arr.dtype.str, "shape": list(arr.shape)}
        ).encode()
        payload = (
            len(header).to_bytes(4, "little") + header + arr.tobytes()
        )
        # one memcpy into shared memory; readers are zero-copy
        self.put_bytes(object_id, payload)

    def get_numpy(self, object_id: str) -> np.ndarray:
        """Returns a read-only view over the shared pages (no copy)."""
        off, size = self.get_buffer(object_id)
        base = self._lib.rtpu_store_base(self._h)
        addr = ctypes.addressof(base.contents) + off
        raw = (ctypes.c_uint8 * size).from_address(addr)
        mv = memoryview(raw)
        hlen = int.from_bytes(mv[:4], "little")
        meta = json.loads(bytes(mv[4 : 4 + hlen]))
        arr = np.frombuffer(
            mv, dtype=np.dtype(meta["dtype"]), offset=4 + hlen
        ).reshape(meta["shape"])
        arr.flags.writeable = False
        return arr

    def delete(self, object_id: str) -> None:
        self._lib.rtpu_store_delete(self._h, self._norm_id(object_id))

    def contains(self, object_id: str) -> bool:
        try:
            off, _ = self.get_buffer(object_id)
            self._lib.rtpu_store_release(self._h, self._norm_id(object_id))
            return True
        except (KeyError, BlockingIOError):
            return False

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        num = ctypes.c_uint64()
        self._lib.rtpu_store_stats(
            self._h, ctypes.byref(cap), ctypes.byref(used), ctypes.byref(num)
        )
        return {
            "capacity": cap.value,
            "used": used.value,
            "num_objects": num.value,
        }

    def close(self, unlink: bool = False) -> None:
        if self._h:
            self._lib.rtpu_store_close(self._h)
            self._h = None
        if unlink or self._owns_file:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass
