// Native fixed-point resource ledger: the grant/reject admission hot path.
//
// C++ equivalent of the reference's LocalResourceManager + FixedPoint
// arithmetic (/root/reference/src/ray/raylet/scheduling/
// local_resource_manager.h:58, src/ray/common/scheduling/fixed_point.h:26):
// per-resource int64 amounts scaled by 1/10000, atomic multi-resource
// try-allocate under a mutex, over-release detection. Consumed from Python
// via ctypes (pure C ABI — no pybind11 in this environment); the node
// agent's every lease admission runs through this.
//
// Capacity model: a fixed-size column vocabulary (indices interned by the
// Python side, scheduling_ids.h:45 analog), dense int64 arrays.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Ledger {
  std::mutex mu;
  std::vector<int64_t> total;
  std::vector<int64_t> avail;
};

}  // namespace

extern "C" {

// Create a ledger with `capacity` resource columns (all zero).
void* rtpu_ledger_create(uint64_t capacity) {
  auto* l = new Ledger();
  l->total.assign(capacity, 0);
  l->avail.assign(capacity, 0);
  return l;
}

void rtpu_ledger_destroy(void* h) { delete static_cast<Ledger*>(h); }

// Grow the column space (vocab interned a new resource name).
int rtpu_ledger_grow(void* h, uint64_t capacity) {
  auto* l = static_cast<Ledger*>(h);
  std::lock_guard<std::mutex> g(l->mu);
  if (capacity < l->total.size()) return -1;
  l->total.resize(capacity, 0);
  l->avail.resize(capacity, 0);
  return 0;
}

// Add capacity to columns: cols[i] += amounts_fp[i] on both total and avail.
int rtpu_ledger_add_capacity(void* h, const uint32_t* cols,
                             const int64_t* amounts_fp, uint64_t n) {
  auto* l = static_cast<Ledger*>(h);
  std::lock_guard<std::mutex> g(l->mu);
  for (uint64_t i = 0; i < n; ++i) {
    if (cols[i] >= l->total.size()) return -1;
    l->total[cols[i]] += amounts_fp[i];
    l->avail[cols[i]] += amounts_fp[i];
  }
  return 0;
}

// Atomic multi-resource admission: all-or-nothing (grant-or-reject).
// Returns 1 on grant, 0 on reject, -1 on bad column.
int rtpu_ledger_try_allocate(void* h, const uint32_t* cols,
                             const int64_t* demands_fp, uint64_t n) {
  auto* l = static_cast<Ledger*>(h);
  std::lock_guard<std::mutex> g(l->mu);
  for (uint64_t i = 0; i < n; ++i) {
    if (cols[i] >= l->avail.size()) return -1;
    if (l->avail[cols[i]] < demands_fp[i]) return 0;
  }
  for (uint64_t i = 0; i < n; ++i) l->avail[cols[i]] -= demands_fp[i];
  return 1;
}

// Release a previously granted demand. Returns -2 on over-release
// (avail would exceed total — a double-release bug), 0 on success.
int rtpu_ledger_release(void* h, const uint32_t* cols,
                        const int64_t* demands_fp, uint64_t n) {
  auto* l = static_cast<Ledger*>(h);
  std::lock_guard<std::mutex> g(l->mu);
  int rc = 0;
  for (uint64_t i = 0; i < n; ++i) {
    if (cols[i] >= l->avail.size()) return -1;
    l->avail[cols[i]] += demands_fp[i];
    if (l->avail[cols[i]] > l->total[cols[i]]) {
      l->avail[cols[i]] = l->total[cols[i]];  // clamp, then report
      rc = -2;
    }
  }
  return rc;
}

// Feasibility (against totals, ignoring current usage).
int rtpu_ledger_is_feasible(void* h, const uint32_t* cols,
                            const int64_t* demands_fp, uint64_t n) {
  auto* l = static_cast<Ledger*>(h);
  std::lock_guard<std::mutex> g(l->mu);
  for (uint64_t i = 0; i < n; ++i) {
    if (cols[i] >= l->total.size()) return -1;
    if (l->total[cols[i]] < demands_fp[i]) return 0;
  }
  return 1;
}

// Snapshot both arrays into caller buffers of size `capacity`.
int rtpu_ledger_snapshot(void* h, int64_t* total_out, int64_t* avail_out,
                         uint64_t capacity) {
  auto* l = static_cast<Ledger*>(h);
  std::lock_guard<std::mutex> g(l->mu);
  if (capacity < l->total.size()) return -1;
  std::memcpy(total_out, l->total.data(), l->total.size() * sizeof(int64_t));
  std::memcpy(avail_out, l->avail.data(), l->avail.size() * sizeof(int64_t));
  return static_cast<int>(l->total.size());
}

}  // extern "C"
