// Shared-memory object store: the plasma equivalent, TPU-host flavored.
//
// Reference behavior being replaced: /root/reference/src/ray/object_manager/
// plasma/store.h:55 (arena allocator + object table + eviction + client
// mapping). This implementation is a single mmap arena with a first-fit
// free-list allocator and an open-addressed object table, all inside the
// mapped region with a process-shared mutex — so any process mapping the
// same file sees the same objects zero-copy (numpy arrays map directly).
//
// C API (ctypes-friendly); all functions return 0 on success, negative on
// error unless documented otherwise.
//
// Layout: [Header | table entries | arena bytes ...]

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5254505553544f53ull;  // "RTPUSTOS" (v2: zombies)
constexpr uint32_t kIdLen = 28;                     // hex id length (like ObjectID)
constexpr uint32_t kEntryEmpty = 0;
constexpr uint32_t kEntryUsed = 1;
constexpr uint32_t kEntryTombstone = 2;
// deleted while readers still hold a pin (zero-copy views): the arena
// space is retained until the last release drops the refcount to zero —
// a mapped numpy view in another process must never see its pages reused
constexpr uint32_t kEntryZombie = 3;

struct Entry {
  char id[kIdLen];
  uint32_t state;
  uint32_t sealed;
  uint64_t offset;  // from arena base
  uint64_t size;
  int64_t refcount;
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // arena bytes
  uint64_t table_slots;   // number of Entry slots
  uint64_t arena_offset;  // file offset of arena base
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t free_count;    // entries in free list
  uint64_t max_free;      // capacity of free list
  pthread_mutex_t mutex;
  // followed by: FreeBlock[max_free], Entry[table_slots], arena
};

struct Store {
  void* base;
  uint64_t total_size;
  Header* hdr;
  FreeBlock* free_list;
  Entry* table;
  uint8_t* arena;
};

uint64_t hash_id(const char* id) {
  uint64_t h = 1469598103934665603ull;
  for (uint32_t i = 0; i < kIdLen; i++) {
    h ^= (uint8_t)id[i];
    h *= 1099511628211ull;
  }
  return h;
}

Entry* find_entry(Store* s, const char* id, bool for_insert) {
  uint64_t slots = s->hdr->table_slots;
  uint64_t h = hash_id(id) % slots;
  Entry* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < slots; probe++) {
    Entry* e = &s->table[(h + probe) % slots];
    if (e->state == kEntryUsed && memcmp(e->id, id, kIdLen) == 0) return e;
    if (e->state == kEntryTombstone && for_insert && !first_tomb)
      first_tomb = e;
    // zombies are invisible to lookups and NOT insertable (they still own
    // arena space); probing continues past them
    if (e->state == kEntryEmpty)
      return for_insert ? (first_tomb ? first_tomb : e) : nullptr;
  }
  return for_insert ? first_tomb : nullptr;
}

// Locate a used-or-zombie entry by id + arena offset (offsets are unique
// per live allocation, so a zombie and its same-id successor never
// collide). Entries never move, so the hash probe still finds them.
Entry* find_entry_at(Store* s, const char* id, uint64_t offset) {
  uint64_t slots = s->hdr->table_slots;
  uint64_t h = hash_id(id) % slots;
  for (uint64_t probe = 0; probe < slots; probe++) {
    Entry* e = &s->table[(h + probe) % slots];
    if ((e->state == kEntryUsed || e->state == kEntryZombie) &&
        e->offset == offset && memcmp(e->id, id, kIdLen) == 0)
      return e;
    if (e->state == kEntryEmpty) return nullptr;
  }
  return nullptr;
}

// first-fit allocation from the free list; splits blocks.
int64_t arena_alloc(Store* s, uint64_t size) {
  size = (size + 63) & ~63ull;  // 64-byte alignment (cache-line)
  Header* h = s->hdr;
  for (uint64_t i = 0; i < h->free_count; i++) {
    FreeBlock* b = &s->free_list[i];
    if (b->size >= size) {
      uint64_t off = b->offset;
      b->offset += size;
      b->size -= size;
      if (b->size == 0) {
        s->free_list[i] = s->free_list[h->free_count - 1];
        h->free_count--;
      }
      h->used_bytes += size;
      return (int64_t)off;
    }
  }
  return -1;
}

void arena_free(Store* s, uint64_t offset, uint64_t size) {
  size = (size + 63) & ~63ull;
  Header* h = s->hdr;
  h->used_bytes -= size;
  // coalesce with an adjacent block when possible
  for (uint64_t i = 0; i < h->free_count; i++) {
    FreeBlock* b = &s->free_list[i];
    if (b->offset + b->size == offset) {
      b->size += size;
      return;
    }
    if (offset + size == b->offset) {
      b->offset = offset;
      b->size += size;
      return;
    }
  }
  if (h->free_count < h->max_free) {
    s->free_list[h->free_count++] = FreeBlock{offset, size};
  }
  // else: leak the block (bounded by max_free fragmentation; acceptable)
}

}  // namespace

extern "C" {

// Create or open a store file of `capacity` arena bytes with `table_slots`
// object slots. Returns an opaque handle or null.
void* rtpu_store_open(const char* path, uint64_t capacity,
                      uint64_t table_slots, int create) {
  uint64_t max_free = table_slots;
  uint64_t header_bytes = sizeof(Header) + max_free * sizeof(FreeBlock) +
                          table_slots * sizeof(Entry);
  header_bytes = (header_bytes + 4095) & ~4095ull;
  uint64_t total = header_bytes + capacity;

  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  fstat(fd, &st);
  bool fresh = st.st_size == 0;
  if (fresh && !create) {
    close(fd);
    return nullptr;
  }
  if (fresh) {
    if (ftruncate(fd, (off_t)total) != 0) {
      close(fd);
      return nullptr;
    }
  } else {
    total = (uint64_t)st.st_size;
  }
  void* base =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;

  Store* s = new Store();
  s->base = base;
  s->total_size = total;
  s->hdr = (Header*)base;
  s->free_list = (FreeBlock*)((uint8_t*)base + sizeof(Header));
  s->table = (Entry*)((uint8_t*)s->free_list + max_free * sizeof(FreeBlock));

  if (fresh) {
    Header* h = s->hdr;
    memset(h, 0, header_bytes);
    h->magic = kMagic;
    h->capacity = capacity;
    h->table_slots = table_slots;
    h->arena_offset = header_bytes;
    h->max_free = max_free;
    h->free_count = 1;
    s->free_list[0] = FreeBlock{0, capacity};
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &attr);
  } else if (s->hdr->magic != kMagic) {
    munmap(base, total);
    delete s;
    return nullptr;
  }
  s->arena = (uint8_t*)base + s->hdr->arena_offset;
  return s;
}

void rtpu_store_close(void* handle) {
  Store* s = (Store*)handle;
  munmap(s->base, s->total_size);
  delete s;
}

static int lock_hdr(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {  // holder died: state is still consistent enough
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}

// Allocate an object buffer. Returns arena offset (>=0) or:
//   -1 out of memory, -2 already exists, -3 table full.
int64_t rtpu_store_create(void* handle, const char* id, uint64_t size) {
  Store* s = (Store*)handle;
  if (lock_hdr(s->hdr) != 0) return -4;
  Entry* e = find_entry(s, id, false);
  if (e != nullptr) {
    pthread_mutex_unlock(&s->hdr->mutex);
    return -2;
  }
  e = find_entry(s, id, true);
  if (e == nullptr) {
    pthread_mutex_unlock(&s->hdr->mutex);
    return -3;
  }
  int64_t off = arena_alloc(s, size);
  if (off < 0) {
    pthread_mutex_unlock(&s->hdr->mutex);
    return -1;
  }
  memcpy(e->id, id, kIdLen);
  e->state = kEntryUsed;
  e->sealed = 0;
  e->offset = (uint64_t)off;
  e->size = size;
  e->refcount = 1;
  s->hdr->num_objects++;
  pthread_mutex_unlock(&s->hdr->mutex);
  return off;
}

int rtpu_store_seal(void* handle, const char* id) {
  Store* s = (Store*)handle;
  if (lock_hdr(s->hdr) != 0) return -4;
  Entry* e = find_entry(s, id, false);
  int rc = 0;
  if (e == nullptr)
    rc = -1;
  else
    e->sealed = 1;
  pthread_mutex_unlock(&s->hdr->mutex);
  return rc;
}

// Look up a sealed object. On success fills offset/size and bumps refcount.
//   0 ok, -1 missing, -2 not sealed.
int rtpu_store_get(void* handle, const char* id, uint64_t* offset,
                   uint64_t* size) {
  Store* s = (Store*)handle;
  if (lock_hdr(s->hdr) != 0) return -4;
  Entry* e = find_entry(s, id, false);
  int rc = 0;
  if (e == nullptr) {
    rc = -1;
  } else if (!e->sealed) {
    rc = -2;
  } else {
    e->refcount++;
    *offset = e->offset;
    *size = e->size;
  }
  pthread_mutex_unlock(&s->hdr->mutex);
  return rc;
}

static void release_entry(Store* s, Entry* e) {
  if (e->refcount > 0) e->refcount--;
  if (e->state == kEntryZombie && e->refcount <= 0) {
    // last pinned reader of a deleted object: free for real now
    arena_free(s, e->offset, e->size);
    e->state = kEntryTombstone;
  }
}

int rtpu_store_release(void* handle, const char* id) {
  Store* s = (Store*)handle;
  if (lock_hdr(s->hdr) != 0) return -4;
  Entry* e = find_entry(s, id, false);
  int rc = e ? 0 : -1;
  if (e) release_entry(s, e);
  pthread_mutex_unlock(&s->hdr->mutex);
  return rc;
}

// Release a long-held pin (zero-copy view) precisely: the (id, offset)
// pair survives a delete (zombie) and is never confused with a same-id
// successor allocation.
int rtpu_store_release_at(void* handle, const char* id, uint64_t offset) {
  Store* s = (Store*)handle;
  if (lock_hdr(s->hdr) != 0) return -4;
  Entry* e = find_entry_at(s, id, offset);
  int rc = e ? 0 : -1;
  if (e) release_entry(s, e);
  pthread_mutex_unlock(&s->hdr->mutex);
  return rc;
}

// Delete: frees arena space when only the creator's share remains;
// otherwise the entry turns zombie and the space is reclaimed by the
// last reader's release (a mapped view must never see reused pages).
int rtpu_store_delete(void* handle, const char* id) {
  Store* s = (Store*)handle;
  if (lock_hdr(s->hdr) != 0) return -4;
  Entry* e = find_entry(s, id, false);
  int rc = 0;
  if (e == nullptr) {
    rc = -1;
  } else if (e->refcount <= 1) {
    arena_free(s, e->offset, e->size);
    e->state = kEntryTombstone;
    e->sealed = 0;
    s->hdr->num_objects--;
  } else {
    e->refcount--;  // consume the creator's share
    e->state = kEntryZombie;
    e->sealed = 0;
    s->hdr->num_objects--;
  }
  pthread_mutex_unlock(&s->hdr->mutex);
  return rc;
}

uint8_t* rtpu_store_base(void* handle) {
  return ((Store*)handle)->arena;
}

// Deleted-with-outstanding-pins entries still holding arena space. A
// nonzero count after every reader released (or died and had its pins
// released by the agent) is a leak; the chaos soak asserts zero.
uint64_t rtpu_store_zombie_count(void* handle) {
  Store* s = (Store*)handle;
  if (lock_hdr(s->hdr) != 0) return 0;
  uint64_t n = 0;
  for (uint64_t i = 0; i < s->hdr->table_slots; i++) {
    if (s->table[i].state == kEntryZombie) n++;
  }
  pthread_mutex_unlock(&s->hdr->mutex);
  return n;
}

void rtpu_store_stats(void* handle, uint64_t* capacity, uint64_t* used,
                      uint64_t* num_objects) {
  Store* s = (Store*)handle;
  *capacity = s->hdr->capacity;
  *used = s->hdr->used_bytes;
  *num_objects = s->hdr->num_objects;
}

}  // extern "C"
