"""On-demand compilation of the native components (no pybind11 — pure C ABI
consumed via ctypes, per the environment constraints)."""
from __future__ import annotations

import os
import subprocess
import threading

_lock = threading.Lock()
_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")

SOURCES = {
    "objstore": "object_store.cc",
    "ledger": "ledger.cc",
    "ring": "ring.cc",
    "wire": "wire.cc",
    "net": "net.cc",
}


def build_native(name: str = "objstore") -> str:
    """Compile (if stale) and return the path to lib<name>.so."""
    src = os.path.join(_HERE, SOURCES[name])
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    with _lock:
        if (
            os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)
        ):
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        # per-pid tmp: concurrent agent processes may compile simultaneously;
        # os.replace keeps the publish atomic either way
        tmp = f"{out}.{os.getpid()}.tmp"
        subprocess.run(
            [
                "g++",
                "-O2",
                "-std=c++17",
                "-shared",
                "-fPIC",
                "-o",
                tmp,
                src,
                "-lpthread",
            ],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, out)
    return out
