"""Pluggable external spill storage (external_storage.py analog).

The reference spills to configurable external storage — local
filesystem or S3-style URIs
(/root/reference/python/ray/_private/external_storage.py). Here a
SpillingStore writes through one of these backends, selected by
``cfg.spill_storage_uri``:

- ``file:///path`` (or a bare path / empty → the node's spill dir):
  atomic local files, the default.
- ``memory://``: in-process dict — the test double.
- ``s3://bucket/prefix``: S3 object storage through boto3 when
  installed, or any injected client exposing
  put_object/get_object/delete_object/head_object (how tests prove the
  path on a zero-egress image, and how non-AWS S3-compatibles slot in).
"""
from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, Optional


class FileSystemBackend:
    """Atomic local files — a unique temp name per write so a concurrent
    spill and duplicate-put fallback for one id never race on one .tmp
    path."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, oid: str) -> str:
        return os.path.join(self.directory, oid)

    def put(self, oid: str, data: bytes) -> None:
        tmp = f"{self._path(oid)}.{uuid.uuid4().hex[:8]}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._path(oid))

    def get(self, oid: str) -> bytes:
        try:
            with open(self._path(oid), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(oid) from None

    def exists(self, oid: str) -> bool:
        return os.path.exists(self._path(oid))

    def delete(self, oid: str) -> None:
        try:
            os.remove(self._path(oid))
        except OSError:
            pass

    def destroy(self) -> None:
        import shutil

        shutil.rmtree(self.directory, ignore_errors=True)


class MemoryBackend:
    def __init__(self):
        self._d: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, oid: str, data: bytes) -> None:
        with self._lock:
            self._d[oid] = data

    def get(self, oid: str) -> bytes:
        with self._lock:
            if oid not in self._d:
                raise KeyError(oid)
            return self._d[oid]

    def exists(self, oid: str) -> bool:
        with self._lock:
            return oid in self._d

    def delete(self, oid: str) -> None:
        with self._lock:
            self._d.pop(oid, None)

    def destroy(self) -> None:
        with self._lock:
            self._d.clear()


class S3Backend:
    """S3-compatible object storage. ``client`` injection is first-class
    (reference external_storage takes a session the same way): pass any
    object with put_object/get_object/delete_object/head_object; without
    one, boto3 is required and its absence is a loud error."""

    def __init__(self, bucket: str, prefix: str = "", client=None):
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        if client is None:
            try:
                import boto3  # type: ignore[import-not-found]
            except ImportError as e:
                raise RuntimeError(
                    "spill_storage_uri=s3://... needs boto3 (not in this "
                    "image) or an injected client"
                ) from e
            client = boto3.client("s3")
        self.client = client

    def _key(self, oid: str) -> str:
        return f"{self.prefix}/{oid}" if self.prefix else oid

    def put(self, oid: str, data: bytes) -> None:
        self.client.put_object(
            Bucket=self.bucket, Key=self._key(oid), Body=data
        )

    def get(self, oid: str) -> bytes:
        try:
            reply = self.client.get_object(
                Bucket=self.bucket, Key=self._key(oid)
            )
        except Exception:  # noqa: BLE001 - NoSuchKey et al.
            raise KeyError(oid) from None
        body = reply["Body"]
        return body.read() if hasattr(body, "read") else body

    def exists(self, oid: str) -> bool:
        try:
            self.client.head_object(Bucket=self.bucket, Key=self._key(oid))
            return True
        except Exception:  # noqa: BLE001
            return False

    def delete(self, oid: str) -> None:
        try:
            self.client.delete_object(
                Bucket=self.bucket, Key=self._key(oid)
            )
        except Exception:  # noqa: BLE001
            pass

    def destroy(self) -> None:
        pass  # remote bucket outlives the node


def storage_from_uri(
    uri: Optional[str], default_dir: str, client=None
):
    """Backend from a spill URI (empty/None → node-local files)."""
    if not uri:
        return FileSystemBackend(default_dir)
    if uri.startswith("file://"):
        return FileSystemBackend(uri[len("file://"):] or default_dir)
    if uri.startswith("memory://"):
        return MemoryBackend()
    if uri.startswith("s3://"):
        rest = uri[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        if not bucket:
            raise ValueError(f"malformed s3 spill uri {uri!r}")
        return S3Backend(bucket, prefix, client=client)
    if "://" not in uri:
        return FileSystemBackend(uri)  # bare path
    raise ValueError(f"unsupported spill storage uri {uri!r}")
