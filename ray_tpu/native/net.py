"""ctypes binding for the C scatter-gather socket plane (net.cc).

One :class:`NetSocket` abstraction over two implementations selected per
construction (``cfg.native_net`` is read live, so tests and the
``RAY_TPU_NATIVE_NET=0`` kill switch flip paths without re-importing):

- **native**: raw fds driven by ``net.cc`` — ``sendmsg`` gather-sends an
  iovec of frame parts (header + arena views, zero joins/copies) and
  ``recv`` loops land bytes straight at arena addresses.
- **python**: the reference-semantics fallback on the stdlib ``socket``
  module (``sendmsg`` / ``recv_into`` keep it scatter/gather too, just
  with per-call interpreter overhead).

Both speak the identical wire bytes — transport.py's parity tests pin
the two byte-for-byte. Also home to the pid-stamped endpoint artifact
helpers (``write_endpoint_file`` / ``sweep_orphan_endpoints``): a
SIGKILLed agent never unlinks its endpoint sidecar, so the next agent on
the host sweeps dead-pid files exactly like ``sweep_orphan_stores``.
"""
from __future__ import annotations

import ctypes
import errno as _errno
import json
import os
import socket
import tempfile
from typing import List, Optional, Sequence, Tuple


class NetClosedError(ConnectionError):
    """The peer closed (or reset) the data socket mid-operation."""


class NetTimeoutError(TimeoutError):
    """A data-socket operation exceeded its I/O deadline."""


def _load_native():
    from .build import build_native

    lib = ctypes.CDLL(build_native("net"))
    lib.rtpu_net_listen.restype = ctypes.c_int
    lib.rtpu_net_listen.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.rtpu_net_local_port.restype = ctypes.c_int
    lib.rtpu_net_local_port.argtypes = [ctypes.c_int]
    lib.rtpu_net_accept.restype = ctypes.c_int
    lib.rtpu_net_accept.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.rtpu_net_connect.restype = ctypes.c_int
    lib.rtpu_net_connect.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.rtpu_net_set_timeout.restype = ctypes.c_int
    lib.rtpu_net_set_timeout.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.rtpu_net_send_vec.restype = ctypes.c_int64
    lib.rtpu_net_send_vec.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint32,
    ]
    lib.rtpu_net_recv_exact.restype = ctypes.c_int64
    lib.rtpu_net_recv_exact.argtypes = [
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_uint64,
    ]
    lib.rtpu_net_close.restype = ctypes.c_int
    lib.rtpu_net_close.argtypes = [ctypes.c_int]
    return lib


_NATIVE = None
_NATIVE_TRIED = False


def native_lib():
    """The compiled net.cc library, or None (toolchain missing). Loaded
    once per process; the per-connection path choice stays live through
    ``native_net_enabled``."""
    global _NATIVE, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE_TRIED = True
        try:
            _NATIVE = _load_native()
        except Exception:  # noqa: BLE001 - toolchain missing: Python path
            _NATIVE = None
        if _NATIVE is not None:
            try:
                # dark-plane counters: register this process's shm slot
                # page so tx/rx bytes count inside the C syscall loop
                from . import counters as _dark_counters

                _dark_counters.register_with_net(_NATIVE)
            except Exception:  # noqa: BLE001 - counting is optional
                pass
    return _NATIVE


def native_net_enabled() -> bool:
    """Kill switch (RAY_TPU_NATIVE_NET, read live) AND toolchain check."""
    try:
        from ray_tpu.config import cfg

        if not cfg.native_net:
            return False
    except Exception:  # noqa: BLE001 - config unavailable (bootstrap)
        if os.environ.get("RAY_TPU_NATIVE_NET", "1").lower() in (
            "0",
            "false",
            "no",
        ):
            return False
    return native_lib() is not None


def _buf_addr(mv) -> Tuple[int, object]:
    """(address, keepalive) for any contiguous buffer, read-only or not
    (ctypes from_buffer refuses read-only views; numpy's zero-copy
    frombuffer hands back the pointer either way — the wire.py idiom)."""
    import numpy as np

    mv = mv if isinstance(mv, memoryview) else memoryview(mv)
    if mv.nbytes == 0:
        return 0, None
    arr = np.frombuffer(mv, dtype=np.uint8)
    return int(arr.ctypes.data), arr


def _raise_net(rc: int, what: str) -> None:
    if rc == -_errno.EAGAIN:
        raise NetTimeoutError(f"{what} timed out")
    if rc in (-_errno.ECONNRESET, 0):
        raise NetClosedError(f"peer closed during {what}")
    raise ConnectionError(f"{what} failed: {os.strerror(-rc) if rc < 0 else rc}")


class NetSocket:
    """One data-plane connection; native fd or Python socket underneath.

    Exactly-once close: every teardown path funnels through
    :meth:`close`, which is idempotent (chaos severs and normal returns
    can race on the same connection)."""

    __slots__ = ("_fd", "_sock", "_closed", "native")

    def __init__(self, fd: Optional[int] = None, sock=None):
        self._fd = fd
        self._sock = sock
        self._closed = False
        self.native = fd is not None

    # -- constructors --------------------------------------------------
    @classmethod
    def connect(
        cls, host: str, port: int, timeout_s: float = 10.0
    ) -> "NetSocket":
        if native_net_enabled():
            lib = native_lib()
            fd = lib.rtpu_net_connect(
                host.encode(), int(port), int(timeout_s * 1000)
            )
            if fd < 0:
                raise ConnectionError(
                    f"connect {host}:{port} failed: {os.strerror(-fd)}"
                )
            return cls(fd=fd)
        sock = socket.create_connection((host, port), timeout=timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return cls(sock=sock)

    # -- I/O -----------------------------------------------------------
    def set_timeout(self, timeout_s: Optional[float]) -> None:
        if self._fd is not None:
            native_lib().rtpu_net_set_timeout(
                self._fd, 0 if timeout_s is None else int(timeout_s * 1000)
            )
        else:
            self._sock.settimeout(timeout_s)

    def send_vec(self, parts: Sequence) -> int:
        """Gather-send every part (bytes / memoryviews) — ONE syscall
        round per kernel window, no user-space join."""
        if self._fd is not None:
            n = len(parts)
            ptrs = (ctypes.c_void_p * n)()
            lens = (ctypes.c_uint64 * n)()
            keep: List[object] = []
            total = 0
            for i, p in enumerate(parts):
                addr, ka = _buf_addr(p)
                ptrs[i] = addr
                nb = p.nbytes if isinstance(p, memoryview) else len(p)
                lens[i] = nb
                total += nb
                keep.append(ka)
            rc = native_lib().rtpu_net_send_vec(self._fd, ptrs, lens, n)
            if rc != total:
                _raise_net(int(rc), "send")
            return total
        try:
            total = sum(
                p.nbytes if isinstance(p, memoryview) else len(p)
                for p in parts
            )
            sent = self._sock.sendmsg(
                [p if isinstance(p, (bytes, memoryview)) else bytes(p) for p in parts]
            )
            # sendmsg may send partially; drain the remainder linearly
            if sent < total:
                joined = b"".join(
                    bytes(p) if isinstance(p, memoryview) else p
                    for p in parts
                )
                self._sock.sendall(joined[sent:])
            from . import counters as _dark_counters

            _dark_counters.add("net_py_tx_bytes_total", total)
            return total
        except socket.timeout as exc:
            raise NetTimeoutError("send timed out") from exc
        except (BrokenPipeError, ConnectionError) as exc:
            raise NetClosedError(f"peer closed during send: {exc}") from exc

    def recv_exact_into(self, mv: memoryview) -> None:
        """Land exactly len(mv) bytes at mv (an arena slice or bytearray
        view) — the scatter-write receiving half."""
        if mv.nbytes == 0:
            return
        if self._fd is not None:
            addr, keep = _buf_addr(mv)
            rc = native_lib().rtpu_net_recv_exact(self._fd, addr, mv.nbytes)
            del keep
            if rc != mv.nbytes:
                _raise_net(int(rc), "recv")
            return
        got = 0
        try:
            while got < mv.nbytes:
                r = self._sock.recv_into(mv[got:], mv.nbytes - got)
                if r == 0:
                    raise NetClosedError("peer closed during recv")
                got += r
            from . import counters as _dark_counters

            _dark_counters.add("net_py_rx_bytes_total", got)
        except socket.timeout as exc:
            raise NetTimeoutError("recv timed out") from exc
        except ConnectionError as exc:
            if isinstance(exc, NetClosedError):
                raise
            raise NetClosedError(f"peer closed during recv: {exc}") from exc

    def recv_exact(self, n: int) -> bytes:
        buf = bytearray(n)
        self.recv_exact_into(memoryview(buf))
        return bytes(buf)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._fd is not None:
            try:
                native_lib().rtpu_net_close(self._fd)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass
        elif self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    @property
    def closed(self) -> bool:
        return self._closed

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class NetListener:
    """Listening socket (native when available — the accept path is not
    hot, but keeping one implementation per connection family means the
    accepted fd and the I/O calls agree)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self._closed = False
        if native_net_enabled():
            lib = native_lib()
            fd = lib.rtpu_net_listen(host.encode(), port)
            if fd < 0:
                raise OSError(f"net listen failed: {os.strerror(-fd)}")
            self._fd: Optional[int] = fd
            self._sock = None
            self.port = int(lib.rtpu_net_local_port(fd))
        else:
            self._fd = None
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self._sock.listen(64)
            self.port = self._sock.getsockname()[1]
        self.address = f"{host}:{self.port}"

    def accept(self, timeout_s: float = 1.0) -> Optional[NetSocket]:
        """One accepted connection, or None on timeout (the accept loop
        polls so shutdown is prompt)."""
        if self._fd is not None:
            fd = native_lib().rtpu_net_accept(self._fd, int(timeout_s * 1000))
            if fd == -_errno.EAGAIN:
                return None
            if fd < 0:
                if self._closed:
                    return None
                raise OSError(f"accept failed: {os.strerror(-fd)}")
            return NetSocket(fd=fd)
        self._sock.settimeout(timeout_s)
        try:
            conn, _ = self._sock.accept()
        except socket.timeout:
            return None
        except OSError:
            if self._closed:
                return None
            raise
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return NetSocket(sock=conn)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._fd is not None:
            try:
                native_lib().rtpu_net_close(self._fd)
            except Exception:  # noqa: BLE001
                pass
        elif self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# pid-stamped endpoint artifacts (hygiene parity with arenas/rings)
# ---------------------------------------------------------------------------


def endpoint_file_path(node_id: str, pid: Optional[int] = None) -> str:
    return os.path.join(
        tempfile.gettempdir(),
        f"ray_tpu_net_{node_id}_{pid or os.getpid()}.ep",
    )


def write_endpoint_file(node_id: str, endpoint: str) -> str:
    """Drop the data-plane endpoint sidecar (operator discovery + orphan
    accounting; the auth token NEVER lands on disk)."""
    path = endpoint_file_path(node_id)
    try:
        with open(path, "w") as f:
            json.dump(
                {"node_id": node_id, "endpoint": endpoint, "pid": os.getpid()},
                f,
            )
    except OSError:
        pass
    return path


def sweep_orphan_endpoints(tmpdir: Optional[str] = None) -> List[str]:
    """Remove ``ray_tpu_net_*.ep`` sidecars whose owning pid is dead (a
    SIGKILLed agent never unlinks its own). Run at agent start beside
    ``sweep_orphan_stores`` / ``sweep_orphan_rings``."""
    import re

    from .shm_store import _pid_alive

    tmpdir = tmpdir or tempfile.gettempdir()
    removed: List[str] = []
    try:
        names = os.listdir(tmpdir)
    except OSError:
        return removed
    pat = re.compile(r"^ray_tpu_net_.*_(\d+)\.ep$")
    for name in names:
        m = pat.match(name)
        if not m:
            continue
        pid = int(m.group(1))
        if pid <= 0 or _pid_alive(pid):
            continue
        path = os.path.join(tmpdir, name)
        try:
            os.unlink(path)
            removed.append(path)
        except OSError:
            pass
    return removed
