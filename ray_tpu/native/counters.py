"""Shm-resident dark-plane counter slots (ISSUE 15).

The zero-Python steady-state paths (compiled-pipeline event loop, the C
framing/socket planes in ``wire.cc``/``net.cc``) cannot afford a locked
``Counter.inc`` per event — and the C halves cannot touch Python at all.
This module gives every process ONE mmap-backed page of int64 slots:

- Python hot wrappers bump slots lock-free through a ``memoryview``
  (single 8-byte store; increments may race a torn observation but
  never corrupt — these are rate indicators, same contract as the
  plain-int ``serialization._stats`` counters);
- the C libraries get the SAME page registered once via
  ``rtpu_wire_set_counters`` / ``rtpu_net_set_counters`` and bump their
  slots with relaxed atomics — bytes and frames are counted where they
  move, with zero FFI or interpreter cost per event;
- observability ticks (agent report loop, head scrape) read the slots
  out into the typed registry via the existing ``sync_counter`` pattern
  (``publish()``), where federation ships them to the head.

The backing file is pid-stamped in the tempdir (like ring/endpoint
sidecars) so a post-mortem can read a SIGKILLed process's last counts;
``sweep_orphan_counters`` reaps dead-pid files at agent start beside
``sweep_orphan_stores``.
"""
from __future__ import annotations

import atexit
import ctypes
import mmap
import os
import tempfile
import threading
from typing import Dict, Optional

#: slot layout — indices are ABI shared with wire.cc / net.cc (their
#: kSlot* constants); append only, never reorder.
SLOTS = (
    "native_wire_c_joins_total",      # 0: frames gather-joined in C
    "native_wire_c_parses_total",     # 1: frames parsed in C
    "native_wire_c_bytes_total",      # 2: frame bytes built in C
    "net_c_tx_bytes_total",           # 3: socket-plane bytes sendmsg'd in C
    "net_c_tx_frames_total",          # 4: sendmsg gather calls in C
    "net_c_rx_bytes_total",           # 5: socket-plane bytes recv'd in C
    "net_py_tx_bytes_total",          # 6: python-fallback socket tx bytes
    "net_py_rx_bytes_total",          # 7: python-fallback socket rx bytes
    "net_stripe_retries_total",       # 8: striped-transfer resume redials
    "pipeline_items_submitted_total",  # 9: compiled-pipeline submits
    "pipeline_items_completed_total",  # 10: compiled-pipeline completions
    "pipeline_items_respilled_total",  # 11: pipeline → eager respills
)

_HELP: Dict[str, str] = {
    "native_wire_c_joins_total": "RTP5 frames gather-joined by wire.cc.",
    "native_wire_c_parses_total": "RTP5 frames parsed by wire.cc.",
    "native_wire_c_bytes_total": "RTP5 frame bytes built by wire.cc.",
    "net_c_tx_bytes_total": "Socket-plane bytes sent by net.cc sendmsg.",
    "net_c_tx_frames_total": "Socket-plane sendmsg gather calls in net.cc.",
    "net_c_rx_bytes_total": "Socket-plane bytes received by net.cc.",
    "net_py_tx_bytes_total": "Socket-plane bytes sent on the Python path.",
    "net_py_rx_bytes_total": "Socket-plane bytes received on the Python path.",
    "net_stripe_retries_total": "Striped-transfer per-stripe resume redials.",
    "pipeline_items_submitted_total": "Compiled-pipeline items submitted.",
    "pipeline_items_completed_total": "Compiled-pipeline items completed.",
    "pipeline_items_respilled_total": "Compiled-pipeline items respilled "
    "to the eager path after a break.",
}

N_SLOTS = 64  # fixed page layout; SLOTS may grow into the tail
assert len(SLOTS) <= N_SLOTS

_PREFIX = "ray_tpu_counters."
_SUFFIX = ".cnt"


class CounterBlock:
    """One process's mmap-backed int64 slot page."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or os.path.join(
            tempfile.gettempdir(), f"{_PREFIX}p{os.getpid()}{_SUFFIX}"
        )
        size = N_SLOTS * 8
        existed = os.path.exists(self.path)
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        if existed:
            # a recycled pid must not inherit a dead process's totals
            # (they'd ship as one giant spurious delta); post-mortem
            # reads only ever target OTHER (dead) pids' pages
            self._mm[:] = b"\0" * size
        self._slots = memoryview(self._mm).cast("q")
        self._closed = False
        # set by register_with_wire/net: once the raw page address is
        # handed to a C library, the mapping must outlive every daemon
        # thread — close() then only unlinks, never unmaps
        self.pinned = False

    # -- hot-path ops (no locks; single-store per bump) ----------------
    def add(self, idx: int, v: int = 1) -> None:
        self._slots[idx] += v

    def get(self, idx: int) -> int:
        return int(self._slots[idx])

    def c_pointer(self) -> ctypes.c_void_p:
        """The page's base address for C-side registration."""
        addr = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
        return ctypes.c_void_p(addr)

    def snapshot(self) -> Dict[str, int]:
        return {name: int(self._slots[i]) for i, name in enumerate(SLOTS)}

    def close(self, unlink: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if not self.pinned:
            # no C library saw the address: safe to unmap. A pinned page
            # stays mapped for the process lifetime — wire.cc/net.cc
            # keep the raw pointer and daemon threads may bump it right
            # through interpreter shutdown.
            try:
                self._slots.release()
                self._mm.close()
            except (BufferError, ValueError):
                pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class _NullBlock:
    """Fallback when the page cannot be created (tempdir unwritable,
    disk full): counting silently no-ops — observability must never
    crash a data-plane hot path that was working."""

    path = None

    def add(self, idx: int, v: int = 1) -> None:
        pass

    def get(self, idx: int) -> int:
        return 0

    def snapshot(self) -> Dict[str, int]:
        return {name: 0 for name in SLOTS}

    def close(self, unlink: bool = True) -> None:
        pass


_lock = threading.Lock()
_block = None  # CounterBlock | _NullBlock
_IDX = {name: i for i, name in enumerate(SLOTS)}


def block():
    """The process's counter page (created on first touch; a no-op
    stand-in on creation failure)."""
    global _block
    if _block is None:
        with _lock:
            if _block is None:
                try:
                    b = CounterBlock()
                    atexit.register(b.close)
                except OSError:
                    b = _NullBlock()
                _block = b
    return _block


def add(name: str, v: int = 1) -> None:
    """Bump one named slot (Python-side dark-path accumulators)."""
    block().add(_IDX[name], v)


def publish() -> Dict[str, int]:
    """Sync every slot into the typed registry (``sync_counter``
    pattern — called from observability ticks, never hot paths)."""
    from ray_tpu.util.metrics import sync_counter

    snap = block().snapshot()
    for name, v in snap.items():
        sync_counter(name, v, _HELP.get(name, ""))
    return snap


def register_with_wire(lib) -> bool:
    """Hand the page to wire.cc (idempotent). Returns False when the
    library predates the counter ABI or the page could not be created."""
    b = block()
    if not isinstance(b, CounterBlock):
        return False
    try:
        fn = lib.rtpu_wire_set_counters
    except AttributeError:
        return False
    fn.restype = None
    fn.argtypes = [ctypes.c_void_p]
    fn(b.c_pointer())
    b.pinned = True
    return True


def register_with_net(lib) -> bool:
    """Hand the page to net.cc (idempotent)."""
    b = block()
    if not isinstance(b, CounterBlock):
        return False
    try:
        fn = lib.rtpu_net_set_counters
    except AttributeError:
        return False
    fn.restype = None
    fn.argtypes = [ctypes.c_void_p]
    fn(b.c_pointer())
    b.pinned = True
    return True


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def sweep_orphan_counters(directory: Optional[str] = None) -> int:
    """Unlink counter pages left by SIGKILLed processes (dead pids only
    — same live-pid protection as the ring/arena sweeps)."""
    directory = directory or tempfile.gettempdir()
    swept = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        if not (name.startswith(_PREFIX) and name.endswith(_SUFFIX)):
            continue
        pid_part = name[len(_PREFIX):-len(_SUFFIX)]
        if not pid_part.startswith("p"):
            continue
        try:
            pid = int(pid_part[1:])
        except ValueError:
            continue
        if _pid_alive(pid):
            continue
        try:
            os.unlink(os.path.join(directory, name))
            swept += 1
        except OSError:
            pass
    return swept
