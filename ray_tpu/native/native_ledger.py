"""ctypes binding for the C++ fixed-point resource ledger (ledger.cc).

Drop-in replacement for the pure-Python NodeResourceLedger
(ray_tpu/scheduler/resources.py): same interface, native admission path
(the LocalResourceManager analog the node agent hits on every lease).
"""
from __future__ import annotations

import ctypes
import threading
from typing import Dict, Mapping

import numpy as np

from .build import build_native

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is None:
            lib = ctypes.CDLL(build_native("ledger"))
            lib.rtpu_ledger_create.restype = ctypes.c_void_p
            lib.rtpu_ledger_create.argtypes = [ctypes.c_uint64]
            lib.rtpu_ledger_destroy.argtypes = [ctypes.c_void_p]
            lib.rtpu_ledger_grow.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            for fn in (
                "rtpu_ledger_add_capacity",
                "rtpu_ledger_try_allocate",
                "rtpu_ledger_release",
                "rtpu_ledger_is_feasible",
            ):
                f = getattr(lib, fn)
                f.restype = ctypes.c_int
                f.argtypes = [
                    ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_uint64,
                ]
            lib.rtpu_ledger_snapshot.restype = ctypes.c_int
            lib.rtpu_ledger_snapshot.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_uint64,
            ]
            _lib = lib
    return _lib


def _as_arrays(fp_map: Dict[int, int]):
    n = len(fp_map)
    cols = (ctypes.c_uint32 * n)(*fp_map.keys())
    amts = (ctypes.c_int64 * n)(*fp_map.values())
    return cols, amts, n


class NativeNodeResourceLedger:
    """Same contract as scheduler.resources.NodeResourceLedger, C++ core."""

    def __init__(self, vocab, total: Mapping[str, float]):
        self.vocab = vocab
        self._lib = _load()
        self._cap = max(vocab.capacity, 16)
        self._h = self._lib.rtpu_ledger_create(self._cap)
        if not self._h:
            raise MemoryError("native ledger allocation failed")
        self.add_capacity(total)

    def _ensure_cap(self) -> None:
        if self.vocab.capacity > self._cap:
            self._cap = self.vocab.capacity
            self._lib.rtpu_ledger_grow(self._h, self._cap)

    def add_capacity(self, extra: Mapping[str, float]) -> None:
        fp = self.vocab.pack_fp(extra)  # interning may grow the vocab...
        self._ensure_cap()  # ...so grow the native arrays after packing
        cols, amts, n = _as_arrays(fp)
        rc = self._lib.rtpu_ledger_add_capacity(self._h, cols, amts, n)
        assert rc == 0, f"native ledger add_capacity failed ({rc})"

    def is_feasible(self, req) -> bool:
        self._ensure_cap()
        cols, amts, n = _as_arrays(req.demands)
        return self._lib.rtpu_ledger_is_feasible(self._h, cols, amts, n) == 1

    def is_available(self, req) -> bool:
        avail = self._snapshot()[1]
        return all(avail[c] >= q for c, q in req.demands.items())

    def try_allocate(self, req) -> bool:
        self._ensure_cap()
        cols, amts, n = _as_arrays(req.demands)
        return self._lib.rtpu_ledger_try_allocate(self._h, cols, amts, n) == 1

    def release(self, req) -> None:
        self._ensure_cap()
        cols, amts, n = _as_arrays(req.demands)
        rc = self._lib.rtpu_ledger_release(self._h, cols, amts, n)
        assert rc != -2, "over-release detected by native ledger"

    def _snapshot(self):
        total = (ctypes.c_int64 * self._cap)()
        avail = (ctypes.c_int64 * self._cap)()
        n = self._lib.rtpu_ledger_snapshot(self._h, total, avail, self._cap)
        if n < 0:  # vocab grew since; retry once at the new capacity
            self._ensure_cap()
            return self._snapshot()
        return np.frombuffer(total, np.int64, n), np.frombuffer(avail, np.int64, n)

    def _fp_to_map(self, arr) -> Dict[str, float]:
        from ray_tpu.scheduler.resources import from_fp

        return {
            self.vocab.name(c): from_fp(int(v))
            for c, v in enumerate(arr)
            if v and c < self.vocab.num_resources
        }

    def total_map(self) -> Dict[str, float]:
        return self._fp_to_map(self._snapshot()[0])

    def avail_map(self) -> Dict[str, float]:
        return self._fp_to_map(self._snapshot()[1])

    def __del__(self):
        try:
            if self._h:
                self._lib.rtpu_ledger_destroy(self._h)
                self._h = None
        except Exception:  # noqa: BLE001
            pass
