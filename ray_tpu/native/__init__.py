"""Native (C++) runtime components, built on demand with the system g++."""
from .build import build_native  # noqa: F401
from .shm_store import NativeObjectStore  # noqa: F401
