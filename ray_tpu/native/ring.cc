// Shared-memory SPSC byte-ring channel for compiled-DAG edges.
//
// TPU-native analog of the reference's compiled-DAG channel substrate
// (/root/reference/python/ray/experimental/channel/shared_memory_channel.py):
// one producer process, one consumer process, a file-backed mmap ring.
// Messages are length-prefixed byte blobs in a power-of-two byte ring;
// payloads (and the length prefix itself) wrap around the ring end, so
// any message up to capacity-4 bytes fits and no tail space is wasted —
// the writer's only wait condition is `capacity - (w - r) >= 4 + len`.
//
// Blocking uses a futex on a 32-bit generation word (one for "data
// available", one for "space available"), so a parked reader wakes in
// microseconds without spinning. All cross-process synchronization is C++
// atomics on the shared pages — Python (via ctypes, GIL released during
// the call) never has to reason about memory ordering.
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <linux/futex.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055524E4732ULL;  // "RTPURNG2"

struct Header {
  uint64_t magic;
  uint64_t capacity;  // ring bytes (power of two)
  alignas(64) std::atomic<uint64_t> write_pos;  // monotonic byte offset
  alignas(64) std::atomic<uint64_t> read_pos;   // monotonic byte offset
  alignas(64) std::atomic<uint32_t> data_gen;   // futex: bumped on write
  alignas(64) std::atomic<uint32_t> space_gen;  // futex: bumped on read
  alignas(64) std::atomic<uint32_t> closed;     // producer hung up
};

struct Ring {
  Header* h;
  uint8_t* data;
  size_t map_len;
};

int futex_wait(std::atomic<uint32_t>* addr, uint32_t expect,
               const timespec* ts) {
  return syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT,
                 expect, ts, nullptr, 0);
}

void futex_wake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE, INT32_MAX,
          nullptr, nullptr, 0);
}

// Wait until gen != seen (or deadline). Returns false on timeout.
bool wait_gen(std::atomic<uint32_t>* gen, uint32_t seen, double timeout_s) {
  timespec ts;
  ts.tv_sec = static_cast<time_t>(timeout_s);
  ts.tv_nsec = static_cast<long>((timeout_s - ts.tv_sec) * 1e9);
  int rc = futex_wait(gen, seen, timeout_s < 0 ? nullptr : &ts);
  if (rc == -1 && errno == ETIMEDOUT) return false;
  return true;  // woken, spurious wake, or value already changed
}

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

// ring<->linear copies that wrap at the capacity boundary
void copy_in(uint8_t* ring, uint64_t cap, uint64_t pos, const uint8_t* src,
             uint64_t len) {
  uint64_t off = pos & (cap - 1);
  uint64_t first = cap - off < len ? cap - off : len;
  std::memcpy(ring + off, src, first);
  if (first < len) std::memcpy(ring, src + first, len - first);
}

void copy_out(const uint8_t* ring, uint64_t cap, uint64_t pos, uint8_t* dst,
              uint64_t len) {
  uint64_t off = pos & (cap - 1);
  uint64_t first = cap - off < len ? cap - off : len;
  std::memcpy(dst, ring + off, first);
  if (first < len) std::memcpy(dst + first, ring, len - first);
}

}  // namespace

extern "C" {

void* rtpu_ring_open(const char* path, uint64_t capacity, int create) {
  // round capacity up to a power of two
  uint64_t cap = 4096;
  while (cap < capacity) cap <<= 1;
  size_t map_len = sizeof(Header) + cap;
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (create) {
    struct stat st;
    if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < map_len) {
      if (ftruncate(fd, map_len) != 0) {
        close(fd);
        return nullptr;
      }
    }
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    map_len = st.st_size;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = static_cast<Header*>(mem);
  if (create) {
    if (h->magic != kMagic) {
      h->capacity = cap;
      h->write_pos.store(0, std::memory_order_relaxed);
      h->read_pos.store(0, std::memory_order_relaxed);
      h->data_gen.store(0, std::memory_order_relaxed);
      h->space_gen.store(0, std::memory_order_relaxed);
      h->closed.store(0, std::memory_order_relaxed);
      std::atomic_thread_fence(std::memory_order_seq_cst);
      h->magic = kMagic;
    }
  } else if (h->magic != kMagic) {
    munmap(mem, map_len);
    return nullptr;
  }
  Ring* r = new Ring{h, reinterpret_cast<uint8_t*>(mem) + sizeof(Header),
                     map_len};
  return r;
}

// Blocks until 4+len free bytes exist (the reader frees space as it
// drains) or the deadline passes.
//  0 = ok, -1 = timeout, -2 = message too large for ring
int rtpu_ring_write(void* rp, const void* buf, uint64_t len, double timeout_s) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->h;
  const uint64_t cap = h->capacity;
  uint64_t need = 4 + len;
  if (need > cap) return -2;
  double deadline = timeout_s < 0 ? -1 : now_s() + timeout_s;
  for (;;) {
    uint64_t w = h->write_pos.load(std::memory_order_relaxed);
    uint64_t rd = h->read_pos.load(std::memory_order_acquire);
    if (cap - (w - rd) >= need) {
      uint32_t len32 = static_cast<uint32_t>(len);
      copy_in(r->data, cap, w, reinterpret_cast<const uint8_t*>(&len32), 4);
      copy_in(r->data, cap, w + 4, static_cast<const uint8_t*>(buf), len);
      h->write_pos.store(w + need, std::memory_order_release);
      h->data_gen.fetch_add(1, std::memory_order_release);
      futex_wake(&h->data_gen);
      return 0;
    }
    // channel torn down: a parked writer must not wait for a reader that
    // will never drain the ring
    if (h->closed.load(std::memory_order_acquire)) return -3;
    // full: re-sample, then futex-park on the reader's generation word
    uint32_t seen = h->space_gen.load(std::memory_order_acquire);
    uint64_t rd2 = h->read_pos.load(std::memory_order_acquire);
    if (rd2 != rd) continue;  // space appeared while sampling
    double remain = -1;
    if (deadline >= 0) {
      remain = deadline - now_s();
      if (remain <= 0) return -1;
    }
    if (!wait_gen(&h->space_gen, seen, remain < 0 ? -1 : remain) &&
        deadline >= 0 && now_s() >= deadline)
      return -1;
  }
}

// Size of the next message, blocking until one arrives.
//  >=0 size, -1 timeout, -3 channel closed and drained
int64_t rtpu_ring_next_size(void* rp, double timeout_s) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->h;
  const uint64_t cap = h->capacity;
  double deadline = timeout_s < 0 ? -1 : now_s() + timeout_s;
  for (;;) {
    uint64_t rd = h->read_pos.load(std::memory_order_relaxed);
    uint64_t w = h->write_pos.load(std::memory_order_acquire);
    if (w != rd) {
      uint32_t len32;
      copy_out(r->data, cap, rd, reinterpret_cast<uint8_t*>(&len32), 4);
      return static_cast<int64_t>(len32);
    }
    if (h->closed.load(std::memory_order_acquire)) return -3;
    uint32_t seen = h->data_gen.load(std::memory_order_acquire);
    if (h->write_pos.load(std::memory_order_acquire) != rd) continue;
    double remain = -1;
    if (deadline >= 0) {
      remain = deadline - now_s();
      if (remain <= 0) return -1;
    }
    if (!wait_gen(&h->data_gen, seen, remain < 0 ? -1 : remain) &&
        deadline >= 0 && now_s() >= deadline)
      return -1;
  }
}

// Copy the next message into buf (must be >= its size; use next_size first).
//  >=0 bytes copied, -1 timeout, -3 closed+drained, -4 buffer too small
int64_t rtpu_ring_read(void* rp, void* buf, uint64_t buflen, double timeout_s) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->h;
  const uint64_t cap = h->capacity;
  int64_t size = rtpu_ring_next_size(rp, timeout_s);
  if (size < 0) return size;
  if (static_cast<uint64_t>(size) > buflen) return -4;
  uint64_t rd = h->read_pos.load(std::memory_order_relaxed);
  copy_out(r->data, cap, rd + 4, static_cast<uint8_t*>(buf), size);
  h->read_pos.store(rd + 4 + size, std::memory_order_release);
  h->space_gen.fetch_add(1, std::memory_order_release);
  futex_wake(&h->space_gen);
  return size;
}

void rtpu_ring_close_write(void* rp) {
  Ring* r = static_cast<Ring*>(rp);
  r->h->closed.store(1, std::memory_order_release);
  r->h->data_gen.fetch_add(1, std::memory_order_release);
  futex_wake(&r->h->data_gen);
  // also wake writers parked on a full ring (teardown stall-breaker)
  r->h->space_gen.fetch_add(1, std::memory_order_release);
  futex_wake(&r->h->space_gen);
}

uint64_t rtpu_ring_capacity(void* rp) {
  return static_cast<Ring*>(rp)->h->capacity;
}

// Bytes currently buffered (unread) in the ring — observability only
// (fill-level gauges); racy by nature, never used for flow control.
// Load order matters even for a racy gauge: read_pos FIRST (like the
// reader path) so a concurrent drain between the loads can only make
// the result small, never underflow w - r past zero.
uint64_t rtpu_ring_used(void* rp) {
  Header* h = static_cast<Ring*>(rp)->h;
  uint64_t r = h->read_pos.load(std::memory_order_acquire);
  uint64_t w = h->write_pos.load(std::memory_order_acquire);
  return w >= r ? w - r : 0;
}

void rtpu_ring_close(void* rp) {
  Ring* r = static_cast<Ring*>(rp);
  munmap(r->h, r->map_len);
  delete r;
}

}  // extern "C"
