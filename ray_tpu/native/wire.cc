// C hot path for the pickle-5 out-of-band wire format (RTP5 frames).
//
// The frame layout is owned by ray_tpu/cluster/serialization.py:
//
//   MAGIC("RTP5") | u16 version | u16 nbufs | u64 pkl_len
//                 | nbufs x u64 buf_len | pickle bytes | raw buffers...
//
// Python keeps the pickling itself (cloudpickle + PickleBuffer
// callbacks are interpreter work by definition); what moves here is the
// *framing*: header pack, buffer-length table scan with overflow-checked
// bounds validation, and the scatter/gather joins. One C call replaces a
// per-buffer Python loop of struct.pack / unpack_from / slice-copies, so
// a frame with dozens of out-of-band buffers costs one FFI hop instead
// of O(nbufs) interpreter ops. serialization.py selects this library at
// import time and keeps the pure-Python implementation as the fallback
// (RAY_TPU_NATIVE_WIRE=0 kill switch, toolchain-missing degrade).
//
// Pure C ABI consumed via ctypes (no pybind11, per the environment
// constraints) — same convention as object_store.cc / ring.cc.
#include <cstdint>
#include <cstring>

namespace {

constexpr char kMagic[4] = {'R', 'T', 'P', '5'};
constexpr uint16_t kVersion = 1;
// MAGIC + u16 version + u16 nbufs + u64 pkl_len
constexpr uint64_t kFixedHeader = 4 + 2 + 2 + 8;

inline void put_u16(uint8_t* p, uint16_t v) { std::memcpy(p, &v, 2); }
inline void put_u64(uint8_t* p, uint64_t v) { std::memcpy(p, &v, 8); }
inline uint16_t get_u16(const uint8_t* p) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  return v;
}
inline uint64_t get_u64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

// Dark-plane counter slots (native/counters.py CounterBlock): an
// mmap-resident int64 page registered once per process. Relaxed atomics
// — rate indicators, not ordering primitives. Slot indices are ABI
// shared with counters.py SLOTS.
long long* g_counters = nullptr;
constexpr int kSlotJoins = 0;
constexpr int kSlotParses = 1;
constexpr int kSlotBytes = 2;

inline void bump(int slot, long long v) {
  if (g_counters)
    __atomic_add_fetch(&g_counters[slot], v, __ATOMIC_RELAXED);
}

}  // namespace

extern "C" {

// Register the shm counter page (nullptr disables). Counting is off
// until the first registration, so standalone users pay nothing.
void rtpu_wire_set_counters(long long* slots) { g_counters = slots; }

// Total frame size for a build with these parts (0 buffers = bare pickle,
// no frame). Overflow-safe: returns 0 on length-table overflow.
uint64_t rtpu_wire_frame_size(uint64_t pkl_len, const uint64_t* buf_lens,
                              uint32_t nbufs) {
  if (nbufs == 0) return pkl_len;
  uint64_t total = kFixedHeader + static_cast<uint64_t>(nbufs) * 8;
  if (total + pkl_len < total) return 0;
  total += pkl_len;
  for (uint32_t i = 0; i < nbufs; ++i) {
    if (total + buf_lens[i] < total) return 0;
    total += buf_lens[i];
  }
  return total;
}

// Gather-join header + pickle + buffers into dst (one pass, one copy per
// part). Returns bytes written, or:
//  -1 dst too small, -2 nbufs exceeds the u16 header field.
int64_t rtpu_wire_join(const uint8_t* pkl, uint64_t pkl_len,
                       const uint8_t* const* bufs, const uint64_t* buf_lens,
                       uint32_t nbufs, uint8_t* dst, uint64_t dst_cap) {
  if (nbufs > 0xFFFF) return -2;
  uint64_t total = rtpu_wire_frame_size(pkl_len, buf_lens, nbufs);
  if (total == 0 || total > dst_cap) return -1;
  if (nbufs == 0) {
    // frame_size's contract: zero buffers = bare pickle, no frame —
    // keep join consistent instead of writing a header it didn't size
    std::memcpy(dst, pkl, pkl_len);
    return static_cast<int64_t>(pkl_len);
  }
  uint8_t* p = dst;
  std::memcpy(p, kMagic, 4);
  p += 4;
  put_u16(p, kVersion);
  p += 2;
  put_u16(p, static_cast<uint16_t>(nbufs));
  p += 2;
  put_u64(p, pkl_len);
  p += 8;
  for (uint32_t i = 0; i < nbufs; ++i) {
    put_u64(p, buf_lens[i]);
    p += 8;
  }
  std::memcpy(p, pkl, pkl_len);
  p += pkl_len;
  for (uint32_t i = 0; i < nbufs; ++i) {
    if (buf_lens[i]) std::memcpy(p, bufs[i], buf_lens[i]);
    p += buf_lens[i];
  }
  bump(kSlotJoins, 1);
  bump(kSlotBytes, static_cast<long long>(p - dst));
  return static_cast<int64_t>(p - dst);
}

// Parse a frame into an offset table. `out` receives
// [pkl_off, pkl_len, buf0_off, buf0_len, buf1_off, buf1_len, ...]
// (2 + 2*max_bufs u64 slots). Returns nbufs (>= 0), or:
//  -1 no RTP5 magic (caller treats data as a plain pickle)
//  -2 truncated or corrupt frame (lengths overrun the data)
//  -3 unknown wire-format version
//  -4 frame has more buffers than max_bufs (caller re-calls with room)
int64_t rtpu_wire_parse(const uint8_t* data, uint64_t len, uint64_t* out,
                        uint32_t max_bufs) {
  if (len < 4 || std::memcmp(data, kMagic, 4) != 0) return -1;
  if (len < kFixedHeader) return -2;
  uint16_t version = get_u16(data + 4);
  if (version != kVersion) return -3;
  uint32_t nbufs = get_u16(data + 6);
  uint64_t pkl_len = get_u64(data + 8);
  uint64_t off = kFixedHeader + static_cast<uint64_t>(nbufs) * 8;
  if (off > len) return -2;
  if (nbufs > max_bufs) return -4;
  const uint8_t* lens = data + kFixedHeader;
  // pickle bounds
  if (pkl_len > len - off) return -2;
  out[0] = off;
  out[1] = pkl_len;
  off += pkl_len;
  for (uint32_t i = 0; i < nbufs; ++i) {
    uint64_t blen = get_u64(lens + static_cast<uint64_t>(i) * 8);
    if (blen > len - off) return -2;
    out[2 + 2 * i] = off;
    out[3 + 2 * i] = blen;
    off += blen;
  }
  bump(kSlotParses, 1);
  return static_cast<int64_t>(nbufs);
}

}  // extern "C"
