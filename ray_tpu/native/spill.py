"""Disk-spilling wrapper around a node's object store.

Analog of the reference's plasma eviction + LocalObjectManager spill/restore
(/root/reference/src/ray/object_manager/plasma/eviction_policy.h,
src/ray/raylet/local_object_manager.h:139-152), collapsed into one layer:

- ``put_bytes`` NEVER hard-errors on a full arena: it spills
  least-recently-used sealed objects to disk until the new object fits, and
  if the object is bigger than what can be freed, the object itself goes to
  disk (create-request backpressure becomes "succeed via disk" instead of
  the reference's queue-and-wait — same liveness, simpler protocol).
- ``get_bytes`` restores from disk transparently (and re-caches into the
  arena when it fits), so readers never observe the spill.
- The distributed GC's DeleteObjects reaches both tiers.

Workers write directly into the shared-memory arena from their own
processes; the agent registers those seals via ``note_external`` so the LRU
book covers them too (it can read any arena object for spilling).
"""
from __future__ import annotations

import os
import shutil
import threading
import uuid
from collections import OrderedDict
from typing import Dict, Optional


class SpillingStore:
    def __init__(
        self,
        inner,
        spill_dir: str,
        capacity: Optional[int] = None,
        headroom_frac: float = 0.1,
    ):
        self.inner = inner
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        stats = getattr(inner, "stats", None)
        self.capacity = capacity or (stats()["capacity"] if stats else 1 << 28)
        self._headroom = int(self.capacity * headroom_frac)
        self._lock = threading.RLock()
        # LRU book of arena-resident objects: oid -> size (insertion order =
        # recency; move_to_end on access)
        self._resident: "OrderedDict[str, int]" = OrderedDict()
        self._spilled: Dict[str, int] = {}  # oid -> size on disk
        self._spilling: set = set()  # victims with a disk write in flight
        self.metrics = {"spilled_objects": 0, "spilled_bytes": 0, "restored": 0}

    # -- paths ---------------------------------------------------------
    def _path(self, oid: str) -> str:
        return os.path.join(self.spill_dir, oid)

    def _write_spill_file(self, oid: str, data: bytes) -> None:
        """Atomic write with a UNIQUE temp name: a concurrent spill and a
        duplicate-put fallback for the same id must never race on one
        .tmp path (os.replace of a vanished tmp is FileNotFoundError)."""
        tmp = f"{self._path(oid)}.{uuid.uuid4().hex[:8]}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._path(oid))

    @property
    def store_path(self) -> str:  # workers map the inner arena
        return getattr(self.inner, "path", "")

    # -- bookkeeping ---------------------------------------------------
    def note_external(self, oid: str, size: int) -> None:
        """A worker sealed this object straight into the shared arena."""
        with self._lock:
            if oid not in self._spilled and self.inner.contains(oid):
                self._resident[oid] = size
                self._resident.move_to_end(oid)

    def _touch(self, oid: str) -> None:
        with self._lock:
            if oid in self._resident:
                self._resident.move_to_end(oid)

    # -- spill machinery ----------------------------------------------
    def _make_room(self, need: int) -> None:
        """Spill LRU residents until ``need`` + headroom fits. Disk writes
        happen OUTSIDE the lock — contains/get/fetch traffic must not queue
        behind file I/O (a full arena would otherwise serialize the whole
        node's object plane on the disk)."""
        stats = getattr(self.inner, "stats", None)
        if stats is None:
            return
        target_free = need + self._headroom
        while True:
            with self._lock:
                s = stats()
                if s["capacity"] - s["used"] >= target_free:
                    return
                # concurrent _make_room callers must not race on one
                # victim: the loser's cleanup would delete the winner's
                # freshly written spill file
                oid = next(
                    (o for o in self._resident if o not in self._spilling),
                    None,
                )
                if oid is None:
                    return
                self._spilling.add(oid)
                try:
                    data = self.inner.get_bytes(oid)
                except Exception:  # noqa: BLE001 - raced a delete
                    self._resident.pop(oid, None)
                    self._spilling.discard(oid)
                    continue
            self._write_spill_file(oid, data)
            with self._lock:
                self._spilling.discard(oid)
                if oid not in self._resident:
                    # deleted (GC) while writing — unless it was spilled by
                    # a competing path, the file must go too
                    if oid not in self._spilled:
                        try:
                            os.remove(self._path(oid))
                        except OSError:
                            pass
                    continue
                try:
                    self.inner.delete(oid)
                except Exception:  # noqa: BLE001
                    pass
                size = self._resident.pop(oid, len(data))
                self._spilled[oid] = size
                self.metrics["spilled_objects"] += 1
                self.metrics["spilled_bytes"] += size

    # -- store interface ----------------------------------------------
    def put_bytes(self, oid: str, data: bytes) -> None:
        with self._lock:
            # duplicate put of an immutable object (task retried after its
            # first execution's reply was lost): already stored, either tier
            if self.inner.contains(oid) or oid in self._spilled:
                return
        for attempt in range(2):
            with self._lock:
                try:
                    self.inner.put_bytes(oid, data)
                    self._resident[oid] = len(data)
                    self._resident.move_to_end(oid)
                    return
                except Exception:  # noqa: BLE001 - arena full (or dup key)
                    if self.inner.contains(oid):
                        return  # duplicate put: already stored
            if attempt == 0:
                self._make_room(len(data))
        # last resort: the new object itself lives on disk
        self._write_spill_file(oid, data)
        with self._lock:
            self._spilled[oid] = len(data)
            self.metrics["spilled_objects"] += 1
            self.metrics["spilled_bytes"] += len(data)

    def get_bytes(self, oid: str) -> bytes:
        with self._lock:
            if self.inner.contains(oid):
                self._touch(oid)
                return self.inner.get_bytes(oid)
            spilled = oid in self._spilled or os.path.exists(self._path(oid))
        if spilled:
            try:
                with open(self._path(oid), "rb") as f:  # outside the lock
                    data = f.read()
            except FileNotFoundError:
                # a concurrent restore_to_arena moved it back to shm
                with self._lock:
                    if self.inner.contains(oid):
                        self._touch(oid)
                        return self.inner.get_bytes(oid)
                raise KeyError(oid) from None
            with self._lock:
                self.metrics["restored"] += 1
            return data
        raise KeyError(oid)

    def restore_to_arena(self, oid: str) -> bool:
        """Bring a spilled object back into shared memory so workers can
        map it (restore path, local_object_manager.h:152)."""
        with self._lock:
            if self.inner.contains(oid):
                self._touch(oid)  # a reader is coming: keep it hot
                return True
            if oid not in self._spilled and not os.path.exists(self._path(oid)):
                return False
            with open(self._path(oid), "rb") as f:
                data = f.read()
            self._make_room(len(data))
            try:
                self.inner.put_bytes(oid, data)
            except Exception:  # noqa: BLE001
                return False
            self._resident[oid] = len(data)
            self._resident.move_to_end(oid)
            self._spilled.pop(oid, None)
            try:
                os.remove(self._path(oid))
            except OSError:
                pass
            self.metrics["restored"] += 1
            return True

    def contains(self, oid: str) -> bool:
        with self._lock:
            return (
                self.inner.contains(oid)
                or oid in self._spilled
                or os.path.exists(self._path(oid))
            )

    def delete(self, oid: str) -> None:
        with self._lock:
            self._resident.pop(oid, None)
            self._spilled.pop(oid, None)
            try:
                self.inner.delete(oid)
            except Exception:  # noqa: BLE001
                pass
            try:
                os.remove(self._path(oid))
            except OSError:
                pass

    def stats(self) -> dict:
        base = getattr(self.inner, "stats", None)
        out = dict(base() if base else {})
        with self._lock:
            out.update(self.metrics)
            out["resident_objects"] = len(self._resident)
            out["spilled_resident"] = len(self._spilled)
        return out

    def close(self, unlink: bool = False) -> None:
        try:
            self.inner.close(unlink=unlink)
        except Exception:  # noqa: BLE001
            pass
        if unlink:
            shutil.rmtree(self.spill_dir, ignore_errors=True)
