"""Disk-spilling wrapper around a node's object store.

Analog of the reference's plasma eviction + LocalObjectManager spill/restore
(/root/reference/src/ray/object_manager/plasma/eviction_policy.h,
src/ray/raylet/local_object_manager.h:139-152), collapsed into one layer:

- ``put_bytes`` NEVER hard-errors on a full arena: it spills
  least-recently-used sealed objects to disk until the new object fits, and
  if the object is bigger than what can be freed, the object itself goes to
  disk (create-request backpressure becomes "succeed via disk" instead of
  the reference's queue-and-wait — same liveness, simpler protocol).
- ``get_bytes`` restores from disk transparently (and re-caches into the
  arena when it fits), so readers never observe the spill.
- The distributed GC's DeleteObjects reaches both tiers.

Workers write directly into the shared-memory arena from their own
processes; the agent registers those seals via ``note_external`` so the LRU
book covers them too (it can read any arena object for spilling).
"""
from __future__ import annotations

import shutil
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.util.metrics import Counter as _Counter

SHM_EVICTIONS = _Counter(
    "shm_store_evictions_total",
    "Arena residents spilled to disk to make room (LRU eviction).",
)


class SpillingStore:
    def __init__(
        self,
        inner,
        spill_dir: str,
        capacity: Optional[int] = None,
        headroom_frac: float = 0.1,
        backend=None,
    ):
        from .spill_storage import FileSystemBackend

        self.inner = inner
        self.spill_dir = spill_dir
        # pluggable external storage (external_storage.py analog):
        # node-local files by default; memory:// / s3:// via
        # cfg.spill_storage_uri at the agent. Only a backend WE created
        # (the per-node default) is destroyed at close: a user-configured
        # shared target (file:// on NFS, an s3 prefix) holds other nodes'
        # objects.
        self._owns_backend = backend is None
        self.backend = backend or FileSystemBackend(spill_dir)
        stats = getattr(inner, "stats", None)
        self.capacity = capacity or (stats()["capacity"] if stats else 1 << 28)
        self._headroom = int(self.capacity * headroom_frac)
        self._lock = threading.RLock()
        # LRU book of arena-resident objects: oid -> size (insertion order =
        # recency; move_to_end on access)
        self._resident: "OrderedDict[str, int]" = OrderedDict()
        self._spilled: Dict[str, int] = {}  # oid -> size on disk
        self._spilling: set = set()  # victims with a disk write in flight
        self.metrics = {"spilled_objects": 0, "spilled_bytes": 0, "restored": 0}

    # -- paths ---------------------------------------------------------
    def _write_spill_file(self, oid: str, data: bytes) -> None:
        self.backend.put(oid, data)

    @property
    def store_path(self) -> str:  # workers map the inner arena
        return getattr(self.inner, "path", "")

    def release_dead_pins(self, pid: int) -> int:
        """Replay a dead reader's view-pin log against the inner arena
        (zombie-pin reclamation); 0 with the in-memory fallback store."""
        fn = getattr(self.inner, "release_dead_pins", None)
        return int(fn(pid)) if fn is not None else 0

    def zombie_count(self) -> int:
        fn = getattr(self.inner, "zombie_count", None)
        return int(fn()) if fn is not None else 0

    # -- bookkeeping ---------------------------------------------------
    def note_external(self, oid: str, size: int) -> None:
        """A worker sealed this object straight into the shared arena."""
        with self._lock:
            if oid not in self._spilled and self.inner.contains(oid):
                self._resident[oid] = size
                self._resident.move_to_end(oid)

    def _touch(self, oid: str) -> None:
        with self._lock:
            if oid in self._resident:
                self._resident.move_to_end(oid)

    # -- spill machinery ----------------------------------------------
    def _make_room(self, need: int) -> None:
        """Spill LRU residents until ``need`` + headroom fits. Disk writes
        happen OUTSIDE the lock — contains/get/fetch traffic must not queue
        behind file I/O (a full arena would otherwise serialize the whole
        node's object plane on the disk)."""
        stats = getattr(self.inner, "stats", None)
        if stats is None:
            return
        target_free = need + self._headroom
        while True:
            with self._lock:
                s = stats()
                if s["capacity"] - s["used"] >= target_free:
                    return
                # concurrent _make_room callers must not race on one
                # victim: the loser's cleanup would delete the winner's
                # freshly written spill file
                oid = next(
                    (o for o in self._resident if o not in self._spilling),
                    None,
                )
                if oid is None:
                    return
                self._spilling.add(oid)
                try:
                    data = self.inner.get_bytes(oid)
                except Exception:  # noqa: BLE001 - raced a delete
                    self._resident.pop(oid, None)
                    self._spilling.discard(oid)
                    continue
            self._write_spill_file(oid, data)
            with self._lock:
                self._spilling.discard(oid)
                if oid not in self._resident:
                    # deleted (GC) while writing — unless it was spilled by
                    # a competing path, the file must go too
                    if oid not in self._spilled:
                        self.backend.delete(oid)
                    continue
                try:
                    self.inner.delete(oid)
                except Exception:  # noqa: BLE001
                    pass
                size = self._resident.pop(oid, len(data))
                self._spilled[oid] = size
                self.metrics["spilled_objects"] += 1
                self.metrics["spilled_bytes"] += size
                SHM_EVICTIONS.inc()

    # -- store interface ----------------------------------------------
    def put_bytes(self, oid: str, data: bytes) -> None:
        with self._lock:
            # duplicate put of an immutable object (task retried after its
            # first execution's reply was lost): already stored, either tier
            if self.inner.contains(oid) or oid in self._spilled:
                return
        for attempt in range(2):
            with self._lock:
                try:
                    self.inner.put_bytes(oid, data)
                    self._resident[oid] = len(data)
                    self._resident.move_to_end(oid)
                    return
                except Exception:  # noqa: BLE001 - arena full (or dup key)
                    if self.inner.contains(oid):
                        return  # duplicate put: already stored
            if attempt == 0:
                self._make_room(len(data))
        # last resort: the new object itself lives on disk
        self._write_spill_file(oid, data)
        with self._lock:
            self._spilled[oid] = len(data)
            self.metrics["spilled_objects"] += 1
            self.metrics["spilled_bytes"] += len(data)

    def put_frames(self, oid: str, frames: Sequence) -> None:
        """Scatter-put of the out-of-band wire frames: writes straight
        into the arena when it fits — including after an LRU spill pass
        when it is full (the zero-copy seal path must not degrade to a
        monolithic join exactly under memory pressure). Only an object
        that cannot fit even after eviction takes the joined put_bytes
        route (which owns the spill-to-disk fallback)."""
        putf = getattr(self.inner, "put_frames", None)
        if putf is not None:
            total = sum(
                f.nbytes if isinstance(f, memoryview) else len(f)
                for f in frames
            )
            for attempt in range(2):
                with self._lock:
                    if self.inner.contains(oid) or oid in self._spilled:
                        return
                    try:
                        putf(oid, frames)
                        self._resident[oid] = total
                        self._resident.move_to_end(oid)
                        return
                    except MemoryError:
                        pass
                    except KeyError:
                        return  # duplicate put: already stored
                if attempt == 0:
                    self._make_room(total)
        data = b"".join(
            bytes(f) if isinstance(f, memoryview) else f for f in frames
        )
        self.put_bytes(oid, data)

    # -- staged puts (cross-node receive path) -------------------------
    def begin_put(self, oid: str, total: int) -> Optional[memoryview]:
        """Stage an arena entry for a cross-node transfer to scatter
        stripes into (spilling LRU residents to make room first).
        Returns None when the arena cannot host it even after eviction
        (or the inner store has no staged-put support) — the receiver
        then lands into host memory and takes the put_bytes route, which
        owns the spill-to-disk fallback."""
        beginner = getattr(self.inner, "begin_put", None)
        if beginner is None:
            return None
        for attempt in range(2):
            with self._lock:
                if self.inner.contains(oid) or oid in self._spilled:
                    raise KeyError(f"object {oid} already in store")
                try:
                    return beginner(oid, total)
                except MemoryError:
                    pass
                except KeyError:
                    # the entry exists but is NOT sealed (contains() was
                    # false): a CONCURRENT transfer is staging the same
                    # object right now. That is not a duplicate — the
                    # other pull may still abort — so land in host
                    # memory instead; the final put_bytes is dup-safe
                    # whichever transfer seals first.
                    return None
            if attempt == 0:
                self._make_room(total)
        return None

    def commit_put(self, oid: str) -> None:
        with self._lock:
            self.inner.commit_put(oid)
            size = getattr(self.inner, "object_size", lambda _o: 0)(oid)
            self._resident[oid] = size
            self._resident.move_to_end(oid)

    def abort_put(self, oid: str) -> None:
        with self._lock:
            aborter = getattr(self.inner, "abort_put", None)
            if aborter is not None:
                aborter(oid)

    def get_range(self, oid: str, offset: int, length: int) -> bytes:
        """One window of an object (chunked peer transfers): arena
        residents slice in place. A spilled object is RESTORED to the
        arena first so a 256-chunk pull reads the backend once, not 256
        times; only when it cannot fit back does each chunk slice a full
        backend read (bounded by the chunk count, and the transfer is
        already in degraded-capacity territory)."""
        ranger = getattr(self.inner, "get_range", None)
        if ranger is not None:
            with self._lock:
                if self.inner.contains(oid):
                    self._touch(oid)
                    return ranger(oid, offset, length)
            if self.restore_to_arena(oid):
                with self._lock:
                    if self.inner.contains(oid):
                        self._touch(oid)
                        return ranger(oid, offset, length)
        data = self.get_bytes(oid)
        return data[offset : offset + length]

    def get_bytes(self, oid: str) -> bytes:
        with self._lock:
            if self.inner.contains(oid):
                self._touch(oid)
                return self.inner.get_bytes(oid)
            spilled = oid in self._spilled
        if not spilled:
            spilled = self.backend.exists(oid)  # network probe: no lock
        if spilled:
            try:
                data = self.backend.get(oid)  # outside the lock
            except KeyError:
                # a concurrent restore_to_arena moved it back to shm
                with self._lock:
                    if self.inner.contains(oid):
                        self._touch(oid)
                        return self.inner.get_bytes(oid)
                raise KeyError(oid) from None
            with self._lock:
                self.metrics["restored"] += 1
            return data
        raise KeyError(oid)

    def restore_to_arena(self, oid: str) -> bool:
        """Bring a spilled object back into shared memory so workers can
        map it (restore path, local_object_manager.h:152)."""
        with self._lock:
            if self.inner.contains(oid):
                self._touch(oid)  # a reader is coming: keep it hot
                return True
            known_spilled = oid in self._spilled
        # backend download OUTSIDE the lock: a remote restore can be a
        # multi-MB network read and must not stall every put/get/contains
        if not known_spilled and not self.backend.exists(oid):
            return False
        try:
            data = self.backend.get(oid)
        except KeyError:
            return False
        self._make_room(len(data))
        with self._lock:
            if self.inner.contains(oid):
                self._touch(oid)
                return True  # raced another restore
            try:
                self.inner.put_bytes(oid, data)
            except Exception:  # noqa: BLE001
                return False
            self._resident[oid] = len(data)
            self._resident.move_to_end(oid)
            self._spilled.pop(oid, None)
            self.metrics["restored"] += 1
        self.backend.delete(oid)
        return True

    def object_size(self, oid: str) -> int:
        """Byte size of a stored object (KeyError when absent) — the
        chunked-fetch handshake sizes the pull without shipping bytes."""
        with self._lock:
            n = self._resident.get(oid)
            if n is None:
                n = self._spilled.get(oid)
            if n is not None:
                return n
            sizer = getattr(self.inner, "object_size", None)
            if sizer is not None and self.inner.contains(oid):
                return sizer(oid)
        return len(self.get_bytes(oid))

    def contains(self, oid: str) -> bool:
        with self._lock:
            if self.inner.contains(oid) or oid in self._spilled:
                return True
        # backend probe OUTSIDE the lock: with a remote backend this is a
        # network round-trip and must not serialize the object plane
        return self.backend.exists(oid)

    def delete(self, oid: str) -> None:
        with self._lock:
            self._resident.pop(oid, None)
            self._spilled.pop(oid, None)
            try:
                self.inner.delete(oid)
            except Exception:  # noqa: BLE001
                pass
        self.backend.delete(oid)  # network call: outside the lock

    def list_objects(self) -> List[Tuple[str, int]]:
        """(oid, size) inventory of everything this node holds — arena
        residents plus spilled entries. The agent advertises this on
        (re-)registration so a restarted head can re-seed its object
        directory."""
        with self._lock:
            out = list(self._resident.items())
            out.extend(self._spilled.items())
        return out

    def stats(self) -> dict:
        base = getattr(self.inner, "stats", None)
        out = dict(base() if base else {})
        with self._lock:
            out.update(self.metrics)
            out["resident_objects"] = len(self._resident)
            out["spilled_resident"] = len(self._spilled)
        return out

    def close(self, unlink: bool = False) -> None:
        try:
            self.inner.close(unlink=unlink)
        except Exception:  # noqa: BLE001
            pass
        if unlink:
            if self._owns_backend:
                destroy = getattr(self.backend, "destroy", None)
                if destroy is not None:
                    destroy()
            shutil.rmtree(self.spill_dir, ignore_errors=True)
