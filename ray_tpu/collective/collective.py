"""Host-backend collective groups (rendezvous over shared memory)."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "product": lambda xs: np.prod(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
}


@dataclass
class _Group:
    name: str
    world_size: int
    barrier: threading.Barrier
    lock: threading.Lock = field(default_factory=threading.Lock)
    slots: List[Any] = field(default_factory=list)
    result: Any = None
    generation: int = 0
    p2p: Dict[tuple, Any] = field(default_factory=dict)
    p2p_cv: threading.Condition = field(default_factory=threading.Condition)


_groups: Dict[str, _Group] = {}
_groups_lock = threading.Lock()
# rank registry keyed by (group, caller identity): for actor methods the
# identity is the actor id — stable across the actor's worker threads
# (max_concurrency > 1) and restarts; plain threads fall back to thread id.
_ranks: Dict[tuple, int] = {}
_ranks_lock = threading.Lock()
# distributed (cross-process) groups, keyed like _ranks
_dist_groups: Dict[tuple, Any] = {}


def _caller_key() -> Any:
    try:
        from ray_tpu.core.runtime import get_context

        actor_id = get_context().actor_id
        if actor_id:
            return ("actor", actor_id)
    except Exception:  # noqa: BLE001 - outside the runtime
        pass
    return ("thread", threading.get_ident())


def _runtime_is_remote() -> bool:
    try:
        from ray_tpu.core.runtime import get_runtime

        return bool(getattr(get_runtime(), "is_remote", False))
    except Exception:  # noqa: BLE001 - runtime not initialized
        return False


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Per-rank group registration (collective.py:146 parity).

    backend="host" rendezvouses in-process (single-process runtime);
    backend="distributed" — or any backend when running against a live
    multi-process cluster — rendezvouses through a named actor reachable
    over DCN (collective/distributed.py)."""
    if backend == "distributed" or _runtime_is_remote():
        from .distributed import create_distributed_group

        group = create_distributed_group(world_size, rank, group_name)
        with _ranks_lock:
            _dist_groups[(group_name, _caller_key())] = group
        return
    with _groups_lock:
        if group_name not in _groups:
            _groups[group_name] = _Group(
                name=group_name,
                world_size=world_size,
                barrier=threading.Barrier(world_size),
            )
        g = _groups[group_name]
        if g.world_size != world_size:
            raise ValueError(
                f"group {group_name} already exists with world_size "
                f"{g.world_size}"
            )
    with _ranks_lock:
        _ranks[(group_name, _caller_key())] = rank


def create_collective_group(
    actors: List[Any],
    world_size: int,
    ranks: List[int],
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Driver-side declaration (collective.py:186): initializes the group on
    every actor via a remote call to ray_tpu.collective.init_collective_group.
    """
    import ray_tpu

    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(
            actor._init_collective.remote(world_size, rank, backend, group_name)
        )
    ray_tpu.get(refs)


def _dist_group(group_name: str):
    with _ranks_lock:
        return _dist_groups.get((group_name, _caller_key()))


def _group_and_rank(group_name: str):
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(f"collective group {group_name!r} not initialized")
    with _ranks_lock:
        rank = _ranks.get((group_name, _caller_key()))
    if rank is None:
        raise RuntimeError(
            f"caller has no rank in group {group_name!r} "
            "(init_collective_group not called from this actor/thread)"
        )
    return g, rank


def get_rank(group_name: str = "default") -> int:
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.rank
    return _group_and_rank(group_name)[1]


def get_collective_group_size(group_name: str = "default") -> int:
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.world
    return _group_and_rank(group_name)[0].world_size


def barrier(group_name: str = "default") -> None:
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.barrier()
    g, _ = _group_and_rank(group_name)
    g.barrier.wait()


def _all_to_driver(g: _Group, rank: int, value: Any) -> List[Any]:
    """Gather all ranks' values; everyone sees the full list."""
    with g.lock:
        if len(g.slots) != g.world_size:
            g.slots = [None] * g.world_size
        g.slots[rank] = value
    g.barrier.wait()
    gathered = list(g.slots)
    g.barrier.wait()  # all have copied before reset
    if rank == 0:
        with g.lock:
            g.slots = []
    g.barrier.wait()  # reset visible to all before the next collective
    return gathered


def allreduce(tensor, op: str = "sum", group_name: str = "default"):
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.allreduce(tensor, op)
    g, rank = _group_and_rank(group_name)
    gathered = _all_to_driver(g, rank, np.asarray(tensor))
    return _REDUCE_OPS[op](np.stack(gathered))


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.allgather(tensor)
    g, rank = _group_and_rank(group_name)
    return [np.asarray(x) for x in _all_to_driver(g, rank, np.asarray(tensor))]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.broadcast(tensor, src_rank)
    g, rank = _group_and_rank(group_name)
    gathered = _all_to_driver(g, rank, np.asarray(tensor))
    return gathered[src_rank]


def reducescatter(tensor, op: str = "sum", group_name: str = "default"):
    """Each rank gets its 1/world_size shard of the reduction."""
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.reducescatter(tensor, op)
    g, rank = _group_and_rank(group_name)
    gathered = _all_to_driver(g, rank, np.asarray(tensor))
    reduced = _REDUCE_OPS[op](np.stack(gathered))
    return np.array_split(reduced, g.world_size)[rank]


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.send(tensor, dst_rank)
    g, rank = _group_and_rank(group_name)
    with g.p2p_cv:
        g.p2p.setdefault((rank, dst_rank), []).append(np.asarray(tensor))
        g.p2p_cv.notify_all()


def recv(src_rank: int, group_name: str = "default", timeout: float = 30.0):
    """Messages are delivered in send order (FIFO per (src, dst) pair)."""
    dg = _dist_group(group_name)
    if dg is not None:
        return dg.recv(src_rank, timeout)
    g, rank = _group_and_rank(group_name)
    key = (src_rank, rank)
    with g.p2p_cv:
        ok = g.p2p_cv.wait_for(lambda: g.p2p.get(key), timeout)
        if not ok:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        queue = g.p2p[key]
        value = queue.pop(0)
        if not queue:
            del g.p2p[key]
        return value


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        _groups.pop(group_name, None)
    doomed = []
    with _ranks_lock:
        for key in [k for k in _dist_groups if k[0] == group_name]:
            doomed.append(_dist_groups.pop(key))
    if doomed:
        from .distributed import destroy_distributed_group

        destroy_distributed_group(doomed[0])


def collective_actor_mixin(cls):
    """Class decorator adding the _init_collective method used by
    create_collective_group."""

    def _init_collective(self, world_size, rank, backend, group_name):
        init_collective_group(world_size, rank, backend, group_name)
        return rank

    cls._init_collective = _init_collective
    return cls
