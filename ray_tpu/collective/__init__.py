"""ray_tpu.util.collective — collective communication API.

API parity with the reference's ray.util.collective
(/root/reference/python/ray/util/collective/collective.py:146-660). Two
planes, TPU-first:

- **In-program (the fast path)**: collectives inside jit over a Mesh are XLA
  collectives on ICI — jax.lax.psum/all_gather/ppermute. That replaces the
  reference's NCCL plane entirely; nothing to manage here.
- **Host-level groups (this module)**: actor/task ranks outside jit
  rendezvous through an in-process "host" backend (the Gloo analog) —
  allreduce/broadcast/allgather/reducescatter/send/recv with barrier
  semantics identical to the reference API.
"""
from .collective import (  # noqa: F401
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_rank,
    get_collective_group_size,
    init_collective_group,
    recv,
    reducescatter,
    send,
)
