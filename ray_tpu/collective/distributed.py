"""Cross-process collective groups: the DCN host-collective backend.

The reference's host collectives are NCCL/Gloo process groups
(/root/reference/python/ray/util/collective/collective_group/). On TPU the
*data-plane* collectives are XLA-on-ICI inside jit; what remains is a
host-level rendezvous across worker processes/hosts — here built on a named
rendezvous actor reachable from every process in the cluster (DCN traffic
rides the same gRPC object plane as everything else).

Actor methods run serially, so the protocol is non-blocking
contribute/poll: every rank posts its contribution, then polls until the
group is complete. Op ids come from per-op monotonic counters, which are
consistent across ranks because collective calls are SPMD-ordered (the
same assumption NCCL makes).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "product": lambda xs: np.prod(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
}

_POLL_S = 0.01


class CollectiveGroupActor:
    """Rendezvous state for one group (runs as a named actor)."""

    def __init__(self, world_size: int):
        self.world = world_size
        self.slots: Dict[str, Dict[int, Any]] = {}
        self.fetched: Dict[str, set] = {}
        self.mailbox: Dict[tuple, Any] = {}

    def world_size(self) -> int:
        return self.world

    def contribute(self, op_id: str, rank: int, value: Any) -> None:
        self.slots.setdefault(op_id, {})[rank] = value

    def poll(self, op_id: str, rank: int) -> Optional[List[Any]]:
        s = self.slots.get(op_id)
        if s is None or len(s) < self.world:
            return None
        out = [s[r] for r in range(self.world)]
        done = self.fetched.setdefault(op_id, set())
        done.add(rank)
        if len(done) == self.world:
            del self.slots[op_id]
            del self.fetched[op_id]
        return out

    # point-to-point
    def put(self, key: tuple, value: Any) -> None:
        self.mailbox[key] = value

    def take(self, key: tuple) -> tuple:
        if key in self.mailbox:
            return (True, self.mailbox.pop(key))
        return (False, None)


class DistributedGroup:
    """Per-process view of one collective group."""

    def __init__(self, handle, world_size: int, rank: int, name: str):
        self.handle = handle
        self.world = world_size
        self.rank = rank
        self.name = name
        self._counters: Dict[str, int] = {}

    def _op_id(self, op: str) -> str:
        n = self._counters.get(op, 0)
        self._counters[op] = n + 1
        return f"{op}:{n}"

    def _rendezvous(self, op: str, value: Any, timeout: float = 120.0) -> List[Any]:
        op_id = self._op_id(op)
        ray_tpu.get(
            self.handle.contribute.remote(op_id, self.rank, value), timeout=60
        )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            out = ray_tpu.get(
                self.handle.poll.remote(op_id, self.rank), timeout=60
            )
            if out is not None:
                return out
            time.sleep(_POLL_S)
        raise TimeoutError(
            f"collective {op_id} in group {self.name!r} timed out "
            f"({self.world} ranks expected)"
        )

    # ------------------------------------------------------------------
    def allreduce(self, tensor, op: str = "sum"):
        values = self._rendezvous("allreduce", np.asarray(tensor))
        return _REDUCE_OPS[op](values)

    def allgather(self, tensor) -> List[np.ndarray]:
        return [np.asarray(v) for v in self._rendezvous("allgather", np.asarray(tensor))]

    def broadcast(self, tensor, src_rank: int = 0):
        values = self._rendezvous("broadcast", np.asarray(tensor))
        return np.asarray(values[src_rank])

    def reducescatter(self, tensor, op: str = "sum"):
        values = self._rendezvous("reducescatter", np.asarray(tensor))
        reduced = _REDUCE_OPS[op](values)
        return np.array_split(reduced, self.world)[self.rank]

    def barrier(self) -> None:
        self._rendezvous("barrier", None)

    def send(self, tensor, dst_rank: int) -> None:
        n = self._counters.get(f"p2p:{self.rank}->{dst_rank}", 0)
        self._counters[f"p2p:{self.rank}->{dst_rank}"] = n + 1
        ray_tpu.get(
            self.handle.put.remote(
                (self.rank, dst_rank, n), np.asarray(tensor)
            ),
            timeout=30,
        )

    def recv(self, src_rank: int, timeout: float = 30.0):
        counter_key = f"p2p:{src_rank}->{self.rank}"
        key_n = self._counters.get(counter_key, 0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            ok, value = ray_tpu.get(
                self.handle.take.remote((src_rank, self.rank, key_n)),
                timeout=30,
            )
            if ok:
                # advance only on success so a timed-out recv can be retried
                # without skipping the in-flight message
                self._counters[counter_key] = key_n + 1
                return value
            time.sleep(_POLL_S)
        raise TimeoutError(f"recv from rank {src_rank} timed out")


def create_distributed_group(
    world_size: int, rank: int, group_name: str
) -> DistributedGroup:
    """Join (creating if first) the named rendezvous actor for this group."""
    actor_name = f"_collective:{group_name}"
    Actor = ray_tpu.remote(CollectiveGroupActor)
    try:
        handle = ray_tpu.get_actor(actor_name)
    except ValueError:
        try:
            handle = Actor.options(name=actor_name).remote(world_size)
        except ValueError:  # lost the creation race
            handle = ray_tpu.get_actor(actor_name)
    existing = ray_tpu.get(handle.world_size.remote(), timeout=60)
    if existing != world_size:
        raise ValueError(
            f"collective group {group_name!r} already exists with "
            f"world_size={existing} (requested {world_size}); destroy it "
            "first or use a distinct group_name per job"
        )
    return DistributedGroup(handle, world_size, rank, group_name)


def destroy_distributed_group(group: DistributedGroup) -> None:
    """Tear down the rendezvous actor so the name can be reused."""
    try:
        ray_tpu.kill(group.handle)
    except Exception:  # noqa: BLE001 - already gone
        pass
