"""Cross-process collective groups: the DCN host-collective backend.

The reference's host collectives are NCCL/Gloo process groups
(/root/reference/python/ray/util/collective/collective_group/). On TPU the
*data-plane* collectives are XLA-on-ICI inside jit; what remains is a
host-level rendezvous across worker processes/hosts — here built on a named
rendezvous actor reachable from every process in the cluster (DCN traffic
rides the same gRPC object plane as everything else).

The rendezvous actor is an *asyncio* actor: every rank makes ONE
``collect`` call that parks on an asyncio.Event until the group is
complete, then returns all contributions — push-based wakeup, no client
polling (pubsub/publisher.h analog for the collective plane; the previous
contribute+poll protocol burned a 100 Hz loop per rank). Op ids come from
per-op monotonic counters, which are consistent across ranks because
collective calls are SPMD-ordered (the same assumption NCCL makes).
"""
from __future__ import annotations

import asyncio
from typing import Any, Dict, List

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "product": lambda xs: np.prod(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
}


class CollectiveGroupActor:
    """Rendezvous state for one group (runs as a named asyncio actor);
    all methods multiplex on the actor's event loop, so Events are safe."""

    def __init__(self, world_size: int):
        self.world = world_size
        self.slots: Dict[str, Dict[int, Any]] = {}
        self.events: Dict[str, asyncio.Event] = {}
        self.remaining: Dict[str, set] = {}
        self.mailbox: Dict[tuple, Any] = {}
        self.mail_events: Dict[tuple, asyncio.Event] = {}

    async def world_size(self) -> int:
        return self.world

    async def collect(
        self, op_id: str, rank: int, value: Any, timeout: float = 120.0
    ):
        """Contribute and await the full group in one round trip. Returns
        None on rendezvous timeout (an explicit sentinel — NOT an
        exception, so callers never have to pattern-match error text); the
        timed-out rank withdraws its contribution so a retry starts
        clean and nothing leaks in the actor."""
        s = self.slots.setdefault(op_id, {})
        s[rank] = value
        ev = self.events.setdefault(op_id, asyncio.Event())
        if len(s) == self.world:
            ev.set()
        else:
            try:
                await asyncio.wait_for(ev.wait(), timeout)
            except asyncio.TimeoutError:
                # the timer can fire in the same loop tick the last rank
                # sets the event: withdrawing then would KeyError innocent
                # ranks mid-gather — re-check before treating it as a miss
                if not ev.is_set():
                    s.pop(rank, None)
                    if not s:
                        self.slots.pop(op_id, None)
                        self.events.pop(op_id, None)
                        self.remaining.pop(op_id, None)
                    return None
        out = [s[r] for r in range(self.world)]
        rem = self.remaining.setdefault(op_id, set(range(self.world)))
        rem.discard(rank)
        if not rem:
            del self.slots[op_id]
            del self.events[op_id]
            del self.remaining[op_id]
        return out

    # point-to-point: the receiver parks on an Event until the sender posts
    async def put(self, key: tuple, value: Any) -> None:
        self.mailbox[key] = value
        ev = self.mail_events.pop(key, None)
        if ev is not None:
            ev.set()

    async def take(self, key: tuple, timeout: float = 30.0) -> tuple:
        if key not in self.mailbox:
            ev = self.mail_events.setdefault(key, asyncio.Event())
            try:
                await asyncio.wait_for(ev.wait(), timeout)
            except asyncio.TimeoutError:
                self.mail_events.pop(key, None)
                return (False, None)
        if key in self.mailbox:
            return (True, self.mailbox.pop(key))
        return (False, None)


class DistributedGroup:
    """Per-process view of one collective group."""

    def __init__(self, handle, world_size: int, rank: int, name: str):
        self.handle = handle
        self.world = world_size
        self.rank = rank
        self.name = name
        self._counters: Dict[str, int] = {}

    def _op_id(self, op: str) -> str:
        n = self._counters.get(op, 0)
        self._counters[op] = n + 1
        return f"{op}:{n}"

    def _rendezvous(self, op: str, value: Any, timeout: float = 120.0) -> List[Any]:
        op_id = self._op_id(op)
        out = ray_tpu.get(
            self.handle.collect.remote(op_id, self.rank, value, timeout),
            timeout=timeout + 30,
        )
        if out is None:
            raise TimeoutError(
                f"collective {op_id} in group {self.name!r} timed out "
                f"({self.world} ranks expected)"
            )
        return out

    # ------------------------------------------------------------------
    def allreduce(self, tensor, op: str = "sum"):
        values = self._rendezvous("allreduce", np.asarray(tensor))
        return _REDUCE_OPS[op](values)

    def allgather(self, tensor) -> List[np.ndarray]:
        return [np.asarray(v) for v in self._rendezvous("allgather", np.asarray(tensor))]

    def broadcast(self, tensor, src_rank: int = 0):
        values = self._rendezvous("broadcast", np.asarray(tensor))
        return np.asarray(values[src_rank])

    def reducescatter(self, tensor, op: str = "sum"):
        values = self._rendezvous("reducescatter", np.asarray(tensor))
        reduced = _REDUCE_OPS[op](values)
        return np.array_split(reduced, self.world)[self.rank]

    def barrier(self) -> None:
        self._rendezvous("barrier", None)

    def send(self, tensor, dst_rank: int) -> None:
        n = self._counters.get(f"p2p:{self.rank}->{dst_rank}", 0)
        self._counters[f"p2p:{self.rank}->{dst_rank}"] = n + 1
        ray_tpu.get(
            self.handle.put.remote(
                (self.rank, dst_rank, n), np.asarray(tensor)
            ),
            timeout=30,
        )

    def recv(self, src_rank: int, timeout: float = 30.0):
        counter_key = f"p2p:{src_rank}->{self.rank}"
        key_n = self._counters.get(counter_key, 0)
        ok, value = ray_tpu.get(
            self.handle.take.remote((src_rank, self.rank, key_n), timeout),
            timeout=timeout + 30,
        )
        if ok:
            # advance only on success so a timed-out recv can be retried
            # without skipping the in-flight message
            self._counters[counter_key] = key_n + 1
            return value
        raise TimeoutError(f"recv from rank {src_rank} timed out")


def create_distributed_group(
    world_size: int, rank: int, group_name: str
) -> DistributedGroup:
    """Join (creating if first) the named rendezvous actor for this group."""
    actor_name = f"_collective:{group_name}"
    Actor = ray_tpu.remote(CollectiveGroupActor)
    try:
        handle = ray_tpu.get_actor(actor_name)
    except ValueError:
        try:
            handle = Actor.options(name=actor_name).remote(world_size)
        except ValueError:  # lost the creation race
            handle = ray_tpu.get_actor(actor_name)
    existing = ray_tpu.get(handle.world_size.remote(), timeout=60)
    if existing != world_size:
        raise ValueError(
            f"collective group {group_name!r} already exists with "
            f"world_size={existing} (requested {world_size}); destroy it "
            "first or use a distinct group_name per job"
        )
    return DistributedGroup(handle, world_size, rank, group_name)


def destroy_distributed_group(group: DistributedGroup) -> None:
    """Tear down the rendezvous actor so the name can be reused."""
    try:
        ray_tpu.kill(group.handle)
    except Exception:  # noqa: BLE001 - already gone
        pass
