"""Scratch: profile the e2e lease hot path (driver side)."""
import cProfile
import pstats
import sys
import time

import ray_tpu
from ray_tpu.cluster import Cluster
from ray_tpu.core.runtime import set_runtime


def _noop():
    return None


def main(n=3000, profile=True):
    c = Cluster()
    c.add_node({"CPU": 16.0}, num_workers=4)
    c.add_node({"CPU": 16.0}, num_workers=4)
    client = c.client()
    set_runtime(client)
    try:
        f = ray_tpu.remote(_noop).options(num_cpus=0.25, max_retries=0)
        ray_tpu.get([f.remote() for _ in range(50)], timeout=60)

        def one_pass(n):
            t0 = time.perf_counter()
            refs = [f.remote() for _ in range(n)]
            for i in range(0, n, 500):
                ray_tpu.get(refs[i:i + 500], timeout=300)
            return n / (time.perf_counter() - t0)

        r1 = one_pass(n)
        if profile:
            pr = cProfile.Profile()
            pr.enable()
            r2 = one_pass(n)
            pr.disable()
            st = pstats.Stats(pr)
            st.sort_stats("cumulative").print_stats(40)
        else:
            r2 = one_pass(n)
        print(f"PASS1 {r1:.1f} tasks/s  PASS2 {r2:.1f} tasks/s")
        print("HEAD METRICS", dict(c.head.metrics))
    finally:
        set_runtime(None)
        c.shutdown()


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3000,
         profile="--no-profile" not in sys.argv)
